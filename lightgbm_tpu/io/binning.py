"""Per-feature value->bin quantization (BinMapper).

Reproduces the reference's binning semantics exactly (bin.cpp:71-246): greedy
equal-count binning with a distinct-value fast path, zero-count handling,
categorical mode, trivial-feature filtering, and searchsorted ValueToBin
(bin.h:385-407).  Host-side NumPy: binning runs once at dataset construction;
the TPU engine consumes only the resulting dense uint8/uint16 bin codes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

NUMERICAL = 0
CATEGORICAL = 1


def _need_filter(cnt_in_bin: Sequence[int], total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """True when no split of this feature can satisfy min_data (bin.cpp:47-69)."""
    if bin_type == NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += cnt_in_bin[i]
            if sum_left >= filter_cnt:
                return False
            if total_cnt - sum_left >= filter_cnt:
                return False
    else:
        for i in range(len(cnt_in_bin) - 1):
            sum_left = cnt_in_bin[i]
            if sum_left >= filter_cnt:
                return False
            if total_cnt - sum_left >= filter_cnt:
                return False
    return True


class BinMapper:
    """Maps raw feature values to dense bin codes.

    Attributes mirror the reference BinMapper (bin.h:55-195): ``num_bin``,
    ``bin_upper_bound`` (numerical) or ``bin_2_categorical`` /
    ``categorical_2_bin`` (categorical), ``default_bin`` (= bin of value 0),
    ``is_trivial``, ``sparse_rate``, ``min_val``/``max_val``.
    """

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.bin_type: int = NUMERICAL
        self.is_trivial: bool = True
        self.sparse_rate: float = 0.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        # FindBin sample occupancy per bin, retained for the drift
        # fingerprint (obs/drift.py); None for mappers restored from
        # pre-drift binary caches
        self.bin_counts: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    def find_bin(self, sample_values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int, min_split_data: int,
                 bin_type: int = NUMERICAL) -> "BinMapper":
        """Compute bin boundaries from sampled non-zero values.

        ``sample_values`` are the sampled non-zero values of the feature;
        zeros are implied: zero_cnt = total_sample_cnt - len(sample_values)
        (bin.cpp:75).
        """
        self.bin_type = bin_type
        self.default_bin = 0
        values = np.asarray(sample_values, dtype=np.float64)
        num_sample_values = len(values)
        zero_cnt = int(total_sample_cnt - num_sample_values)

        # Distinct values with zero spliced into sorted position, counting
        # the implied zeros (bin.cpp:77-110).  Vectorized via np.unique.
        uniq, ucnt = np.unique(values, return_counts=True)
        if zero_cnt > 0 or num_sample_values == 0:
            if 0.0 not in uniq:
                pos = int(np.searchsorted(uniq, 0.0))
                uniq = np.insert(uniq, pos, 0.0)
                ucnt = np.insert(ucnt, pos, zero_cnt)
        distinct_values = uniq.tolist()
        counts = ucnt.astype(np.int64).tolist()

        self.min_val = distinct_values[0]
        self.max_val = distinct_values[-1]
        num_distinct = len(distinct_values)
        cnt_in_bin: List[int] = []

        if bin_type == NUMERICAL:
            if num_distinct <= max_bin:
                # Distinct-value fast path (bin.cpp:116-131).
                bounds: List[float] = []
                cur_cnt = 0
                for i in range(num_distinct - 1):
                    cur_cnt += counts[i]
                    if cur_cnt >= min_data_in_bin:
                        bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                        cnt_in_bin.append(cur_cnt)
                        cur_cnt = 0
                cur_cnt += counts[-1]
                cnt_in_bin.append(cur_cnt)
                bounds.append(np.inf)
                self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
                self.num_bin = len(bounds)
            else:
                # Greedy equal-count path (bin.cpp:132-191).
                if min_data_in_bin > 0:
                    max_bin = max(1, min(max_bin, total_sample_cnt // min_data_in_bin))
                mean_bin_size = total_sample_cnt / max_bin
                if zero_cnt > mean_bin_size:
                    max_bin = min(max_bin, 1 + num_sample_values // max(1, min_data_in_bin))
                counts_arr = np.asarray(counts, dtype=np.int64)
                is_big = counts_arr >= mean_bin_size
                rest_bin_cnt = max_bin - int(is_big.sum())
                rest_sample_cnt = total_sample_cnt - int(counts_arr[is_big].sum())
                mean_bin_size = rest_sample_cnt / max(1, rest_bin_cnt)
                # Prefix sums for O(max_bin) skip-ahead instead of the
                # reference's O(num_distinct) scan: within one bin the
                # boundary test uses a constant mean_bin_size, so the next
                # boundary index is a searchsorted on cumulative counts.
                cum = np.cumsum(counts_arr)              # cum[i] = counts[0..i]
                small = np.where(is_big, 0, counts_arr)
                cum_small = np.cumsum(small)
                big_idx = np.nonzero(is_big)[0]
                upper_bounds = [np.inf] * max_bin
                lower_bounds = [np.inf] * max_bin
                bin_cnt = 0
                lower_bounds[0] = distinct_values[0]
                i_start = 0                               # first distinct idx of bin
                while i_start <= num_distinct - 2:
                    base = cum[i_start - 1] if i_start > 0 else 0
                    # candidate 1: cumulative count reaches mean_bin_size
                    j = int(np.searchsorted(cum, base + mean_bin_size, side="left"))
                    # candidate 2: a big-count value forces its own boundary
                    bpos = int(np.searchsorted(big_idx, i_start))
                    nb = int(big_idx[bpos]) if bpos < len(big_idx) else num_distinct
                    j = min(j, nb)
                    # candidate 3: value right before a big one closes early at
                    # half the mean size (bin.cpp:166-167)
                    if nb - 1 >= i_start and nb - 1 < j:
                        if cum[nb - 1] - base >= max(1.0, mean_bin_size * 0.5):
                            j = nb - 1
                    if j > num_distinct - 2:
                        break
                    cur_cnt = int(cum[j] - base)
                    upper_bounds[bin_cnt] = distinct_values[j]
                    cnt_in_bin.append(cur_cnt)
                    bin_cnt += 1
                    lower_bounds[bin_cnt] = distinct_values[j + 1]
                    if bin_cnt >= max_bin - 1:
                        break
                    # Non-big values consumed so far always come off
                    # rest_sample_cnt; the running mean is only re-derived at a
                    # non-big boundary (bin.cpp:161-177).
                    consumed = cum_small[j] - (cum_small[i_start - 1] if i_start > 0 else 0)
                    rest_sample_cnt -= int(consumed)
                    if not is_big[j]:
                        rest_bin_cnt -= 1
                        mean_bin_size = rest_sample_cnt / max(1, rest_bin_cnt)
                    i_start = j + 1
                # The rows in the loop after `break` (or the last distinct
                # value) land in the final bin (bin.cpp:180-182).
                remaining = total_sample_cnt - sum(cnt_in_bin)
                cnt_in_bin.append(remaining)
                bin_cnt += 1
                bounds = [np.inf] * bin_cnt
                for i in range(bin_cnt - 1):
                    bounds[i] = (upper_bounds[i] + lower_bounds[i + 1]) / 2.0
                self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
                self.num_bin = bin_cnt
        else:
            # Categorical: distinct ints sorted by count desc; keep the most
            # frequent until 98% coverage (bin.cpp:193-225).
            dv_int: List[int] = [int(distinct_values[0])]
            cnts_int: List[int] = [counts[0]]
            for i in range(1, num_distinct):
                iv = int(distinct_values[i])
                if iv != dv_int[-1]:
                    dv_int.append(iv)
                    cnts_int.append(counts[i])
                else:
                    cnts_int[-1] += counts[i]
            order = sorted(range(len(dv_int)), key=lambda i: (-cnts_int[i], dv_int[i]))
            dv_int = [dv_int[i] for i in order]
            cnts_int = [cnts_int[i] for i in order]
            cut_cnt = int(total_sample_cnt * 0.98)
            self.categorical_2_bin = {}
            self.bin_2_categorical = []
            self.num_bin = 0
            used_cnt = 0
            max_bin = min(len(dv_int), max_bin)
            while (used_cnt < cut_cnt or self.num_bin < max_bin) and self.num_bin < len(dv_int):
                cat = dv_int[self.num_bin]
                self.bin_2_categorical.append(cat)
                self.categorical_2_bin[cat] = self.num_bin
                used_cnt += cnts_int[self.num_bin]
                self.num_bin += 1
            cnt_in_bin = cnts_int[: self.num_bin]
            cnt_in_bin[-1] += total_sample_cnt - used_cnt

        # Trivial-feature detection (bin.cpp:227-236).
        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and _need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data, bin_type):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
        self.sparse_rate = cnt_in_bin[self.default_bin] / max(1, total_sample_cnt)
        self.bin_counts = np.asarray(cnt_in_bin[: self.num_bin], np.int64)
        return self

    # ------------------------------------------------------------------
    def value_to_bin(self, values) -> np.ndarray:
        """Vectorized ValueToBin (bin.h:385-407)."""
        values = np.asarray(values, dtype=np.float64)
        scalar = values.ndim == 0
        values = np.atleast_1d(values)
        if self.bin_type == NUMERICAL:
            if values.size >= 65536:
                from .native import values_to_bins_native
                native = values_to_bins_native(
                    values, self.bin_upper_bound,
                    np.uint16 if self.num_bin > 256 else np.uint8)
                if native is not None:
                    return (native.astype(np.int64)[0] if scalar
                            else native.astype(np.int64))
            # First bound >= value.  NaN lands in bin 0 like the reference's
            # binary search (bin.h:385-407: `upper_bounds[m] < v` is false
            # for NaN) — searchsorted alone would put it in the last bin.
            bins = np.searchsorted(self.bin_upper_bound[:-1], values, side="left")
            bins = np.where(np.isnan(values), 0, bins)
        else:
            bins = np.full(values.shape, self.num_bin - 1, dtype=np.int64)
            ints = values.astype(np.int64)
            for cat, b in self.categorical_2_bin.items():
                bins[ints == cat] = b
        bins = bins.astype(np.int64)
        return bins[0] if scalar else bins

    def bin_to_value(self, bin_idx: int) -> float:
        if self.bin_type == NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # ------------------------------------------------------------------
    def feature_info(self) -> str:
        """The ``feature_infos`` model-file entry: ``[min:max]`` for numerical,
        colon-joined categories for categorical, ``none`` for trivial
        (mirrors dataset.cpp feature_infos serialization)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == NUMERICAL:
            return f"[{self.min_val:g}:{self.max_val:g}]"
        return ":".join(str(c) for c in self.bin_2_categorical)

    def to_state(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "bin_counts": (self.bin_counts.tolist()
                           if self.bin_counts is not None else None),
        }

    @classmethod
    def from_state(cls, state: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(state["num_bin"])
        m.bin_type = int(state["bin_type"])
        m.is_trivial = bool(state["is_trivial"])
        m.sparse_rate = float(state["sparse_rate"])
        m.bin_upper_bound = np.asarray(state["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(c) for c in state["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(state["min_val"])
        m.max_val = float(state["max_val"])
        m.default_bin = int(state["default_bin"])
        # absent in pre-drift caches: fingerprinting quietly abstains
        bc = state.get("bin_counts")
        m.bin_counts = np.asarray(bc, np.int64) if bc is not None else None
        return m
