"""Exclusive feature bundling (EFB): the host-side planner.

Wide-sparse workloads (CTR-style one-hot blocks) pay the full ``[F, B]``
histogram cost for every feature even though most features are zero on
most rows.  EFB (the reference's ``enable_bundle``/``max_conflict_rate``,
src/io/dataset.cpp bundling pass) packs *mutually exclusive* sparse
features — features that are rarely non-default on the same row — into
shared columns whose bin space is partitioned into per-member sub-ranges
(offset encoding, reference FeatureGroup style).  The device bin matrix
shrinks from ``[F, N]`` to ``[C, N]``; histograms are built per column
and expanded back to original-feature space before split finding
(``ops/bundle.py``), so trees, the model text format, prediction and the
whole serve path stay in original feature space by construction.

Planner (:func:`plan_bundles`): greedy graph coloring over the mapper
sample — candidates are non-trivial NUMERICAL features whose default bin
is 0 (value 0 binned into bin 0 — the sparse-feature shape) with
``sparse_rate`` >= :data:`MIN_BUNDLE_SPARSE_RATE`, ranked sparsest
first.  A feature joins a bundle when (a) the bundle's cumulative
conflict count (rows where both the bundle and the feature are
non-default) stays within ``max_conflict_rate * sample_rows`` and (b)
the bundle's total bin budget stays within ``max_bin`` (so the bundled
columns ride the existing ``[C, max_bin]`` histogram shapes and uint8
storage unchanged).  Conflicting rows keep the LAST member's value in
column order — the bounded approximation EFB trades for the histogram
savings; ``max_conflict_rate=0`` admits only perfectly exclusive
features, which is what makes the zero-conflict bit-parity pin
(tests/test_bundling.py) meaningful.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ..utils import log

# Candidates must be at least this sparse (BinMapper.sparse_rate = share
# of rows in the default bin).  Denser features gain little from
# bundling and burn conflict budget.
MIN_BUNDLE_SPARSE_RATE = 0.8


class BundlePlan:
    """The bundling decision: which used features share which column.

    ``column_members[c]`` lists the inner (used) feature indices stored
    in column ``c``; ``column_offsets[c]`` gives each member's offset —
    the column slot of that member's local bin 1 — with offset 0 marking
    an identity-encoded singleton (its column IS its own bin codes).
    """

    def __init__(self, column_members: List[List[int]],
                 column_offsets: List[List[int]], num_features: int,
                 sample_conflicts: int = 0):
        self.column_members = [list(m) for m in column_members]
        self.column_offsets = [list(o) for o in column_offsets]
        self.num_features = int(num_features)
        self.sample_conflicts = int(sample_conflicts)

    # -- shape accessors -------------------------------------------------
    @property
    def num_columns(self) -> int:
        return len(self.column_members)

    @property
    def bundles(self) -> List[List[int]]:
        """Multi-member columns only."""
        return [m for m in self.column_members if len(m) > 1]

    @property
    def features_bundled(self) -> int:
        return sum(len(m) for m in self.bundles)

    def signature(self) -> tuple:
        """Cheap equality key for Dataset::CheckAlign-style alignment."""
        return (tuple(tuple(m) for m in self.column_members),
                tuple(tuple(o) for o in self.column_offsets))

    # -- encoding --------------------------------------------------------
    def encode_columns(self, feature_bins: Callable[[int], np.ndarray],
                       n: int, dtype) -> np.ndarray:
        """[C, n] column bin codes from per-feature bin codes.

        ``feature_bins(inner)`` returns that used feature's original bin
        codes for the n rows.  Bundle members write their non-default
        bins at ``offset + bin - 1``; on a conflicting row the LAST
        member in column order wins (deterministic)."""
        out = np.zeros((self.num_columns, n), dtype)
        for c, (members, offsets) in enumerate(
                zip(self.column_members, self.column_offsets)):
            if len(members) == 1 and offsets[0] == 0:
                out[c] = feature_bins(members[0]).astype(dtype)
                continue
            col = np.zeros(n, np.int64)
            for f, off in zip(members, offsets):
                vb = np.asarray(feature_bins(f), np.int64)
                nz = vb > 0          # candidates have default_bin == 0
                col[nz] = off + vb[nz] - 1
            out[c] = col.astype(dtype)
        return out

    # -- device decode tables (ops/bundle.py BundleDecode) ---------------
    def decode_arrays(self, num_bins: Sequence[int],
                      default_bins: Sequence[int], max_bin: int) -> dict:
        """Numpy decode tables for :class:`ops.bundle.BundleDecode`.

        ``num_bins``/``default_bins`` are per used original feature; the
        slot map routes each feature's default bin (and any bin past its
        range) to the zero slot ``max_bin`` so the expansion's integer
        default-bin reconstruction never double-counts."""
        F, B = self.num_features, int(max_bin)
        col = np.zeros(F, np.int32)
        off = np.zeros(F, np.int32)
        width = np.zeros(F, np.int32)
        slot_map = np.full((F, B), B, np.int32)
        default = np.zeros(F, np.int32)
        for c, (members, offsets) in enumerate(
                zip(self.column_members, self.column_offsets)):
            for f, o in zip(members, offsets):
                nb = int(num_bins[f])
                col[f] = c
                off[f] = o
                width[f] = max(nb - 1, 0)
                default[f] = int(default_bins[f])
                if o == 0:
                    b = np.arange(min(nb, B))
                    slot_map[f, b] = b
                else:
                    b = np.arange(1, min(nb, B + 1))
                    slot_map[f, b] = o + b - 1
                if 0 <= default[f] < B:
                    slot_map[f, default[f]] = B
        return {"col": col, "off": off, "width": width,
                "slot_map": slot_map, "default_bin": default}

    # -- serialization (binary dataset cache) ----------------------------
    def to_state(self) -> dict:
        return {"column_members": self.column_members,
                "column_offsets": self.column_offsets,
                "num_features": self.num_features,
                "sample_conflicts": self.sample_conflicts}

    @classmethod
    def from_state(cls, state: Optional[dict]) -> Optional["BundlePlan"]:
        if not state:
            return None
        return cls([list(map(int, m)) for m in state["column_members"]],
                   [list(map(int, o)) for o in state["column_offsets"]],
                   int(state["num_features"]),
                   int(state.get("sample_conflicts", 0)))


def _is_candidate(mapper) -> bool:
    from .binning import NUMERICAL
    return (not mapper.is_trivial
            and mapper.bin_type == NUMERICAL
            and mapper.default_bin == 0
            and mapper.num_bin > 1
            and mapper.sparse_rate >= MIN_BUNDLE_SPARSE_RATE)


def plan_bundles(sample: np.ndarray, mappers, used_feature_map,
                 *, max_conflict_rate: float, max_total_bin: int,
                 enable_bundle: bool = True,
                 is_enable_sparse: bool = True) -> Optional[BundlePlan]:
    """Greedy conflict-bounded bundling over the mapper sample.

    Args:
      sample: [S, F_real] raw sampled rows (the same sample FindBin saw).
      mappers: per-USED-feature BinMapper list.
      used_feature_map: used index -> real column in ``sample``.
      max_conflict_rate: allowed conflicting-row share per bundle.
      max_total_bin: bin budget per bundled column (cfg.max_bin, so the
        existing [C, max_bin] histogram shapes hold).
    Returns a BundlePlan when at least one multi-member bundle formed,
    else None (the dataset stays in plain per-feature layout).
    """
    if not enable_bundle or not is_enable_sparse or len(mappers) == 0:
        return None
    try:
        from ..parallel.multihost import process_rank_world
        if process_rank_world()[1] > 1:
            # each rank loads its own shard: independently-drawn plans
            # would desync the replicated feature space pod-wide
            from .. import obs
            obs.set_gauge("efb_disabled_multihost", 1)
            log.warn_once("efb_multihost",
                          "enable_bundle: feature bundling is disabled "
                          "under multihost loading (per-rank samples "
                          "would draw diverging bundle plans)")
            return None
    except Exception:  # pragma: no cover - uninitialized backend
        pass
    from .. import obs
    with obs.span("Bin::bundle"):
        plan = _plan_bundles_impl(sample, mappers, used_feature_map,
                                  max_conflict_rate, max_total_bin)
    if plan is not None:
        obs.set_gauge("efb_bundles", len(plan.bundles))
        obs.set_gauge("efb_features_bundled", plan.features_bundled)
        obs.set_gauge("efb_columns", plan.num_columns)
        # the one-line dataset sparsity summary (reference-style)
        n_sparse = sum(1 for m in mappers if _is_candidate(m))
        log.info("EFB: %d sparse feature(s), %d bundled into %d bundle(s) "
                 "(%d -> %d columns, %d conflicting sample rows)",
                 n_sparse, plan.features_bundled, len(plan.bundles),
                 plan.num_features, plan.num_columns,
                 plan.sample_conflicts)
    return plan


def _plan_bundles_impl(sample, mappers, used_feature_map,
                       max_conflict_rate, max_total_bin):
    F = len(mappers)
    S = sample.shape[0]
    cand = [f for f in range(F) if _is_candidate(mappers[f])]
    if len(cand) < 2:
        return None
    # sparsest first: the emptiest features pack tightest and burn the
    # least conflict budget (the ISSUE's sparse_rate ranking)
    cand.sort(key=lambda f: (-mappers[f].sparse_rate, f))
    nondefault = {}
    for f in cand:
        col = sample[:, used_feature_map[f]]
        nondefault[f] = np.asarray(
            mappers[f].value_to_bin(col)) != 0
    budget = int(float(max_conflict_rate) * S)

    bundles: List[List[int]] = []       # member lists
    occupied: List[np.ndarray] = []     # per-bundle any-member-nonzero
    conflicts: List[int] = []           # per-bundle cumulative conflicts
    bins_used: List[int] = []           # per-bundle 1 + sum(nb - 1)
    for f in cand:
        nd = nondefault[f]
        nb = int(mappers[f].num_bin)
        placed = False
        for bi in range(len(bundles)):
            if bins_used[bi] + (nb - 1) > max_total_bin:
                continue
            c = int(np.count_nonzero(occupied[bi] & nd))
            if conflicts[bi] + c > budget:
                continue
            bundles[bi].append(f)
            occupied[bi] |= nd
            conflicts[bi] += c
            bins_used[bi] += nb - 1
            placed = True
            break
        if not placed:
            bundles.append([f])
            occupied.append(nd.copy())
            conflicts.append(0)
            bins_used.append(1 + (nb - 1))
    keep = {}
    total_conflicts = 0
    for bi, members in enumerate(bundles):
        if len(members) > 1:
            for f in members:
                keep[f] = bi
            total_conflicts += conflicts[bi]
    if not keep:
        return None

    # column order: walk used features ascending; a bundle's column sits
    # at its first member's position, members sorted ascending (the
    # deterministic conflict-overwrite order)
    emitted = set()
    column_members: List[List[int]] = []
    column_offsets: List[List[int]] = []
    for f in range(F):
        if f in emitted:
            continue
        bi = keep.get(f)
        if bi is None:
            column_members.append([f])
            column_offsets.append([0])
            emitted.add(f)
            continue
        members = sorted(bundles[bi])
        offs = []
        o = 1
        for m in members:
            offs.append(o)
            o += int(mappers[m].num_bin) - 1
        column_members.append(members)
        column_offsets.append(offs)
        emitted.update(members)
    return BundlePlan(column_members, column_offsets, F,
                      sample_conflicts=total_conflicts)
