"""Malformed-input containment at the file-ingest boundary.

The reference parser (src/io/parser.cpp + dataset_loader.cpp) treats
dirty data as a *named, bounded* event: NA spellings become missing
values, a malformed line gets a diagnostic naming the file and line, and
loading either stops cleanly or skips the row.  Before this module, one
bad token anywhere in a million-row file killed a training run with a
bare ``ValueError`` from ``float()`` — or worse, a negative LibSVM
column index silently wrote into the wrong feature.

:class:`IngestGuard` is the per-file containment policy every parser
entry point (``io/parser.py``, ``io/streaming.py``, and the native
loader's fallback) routes classified bad rows through:

- ``bad_data_policy=fail_fast`` (default): the first bad line raises
  :class:`~..utils.log.LightGBMError` naming ``file:line``, the
  classified reason, and the offending token;
- ``bad_data_policy=quarantine``: the line is skipped, appended to the
  quarantine sink ``<data>.quarantine`` (tab-separated
  ``line  reason  detail  raw-line`` records under a ``#`` header), and
  counted in the ``bad_rows_total`` / ``bad_rows_<reason>`` obs
  counters — until the error budget (``max_bad_rows`` absolute,
  ``max_bad_row_fraction`` relative) is exhausted, at which point the
  load fails with a budget diagnostic.  A file that is mostly garbage
  is a *file* problem, not a row problem.

Classification reasons (:data:`REASONS`): ``unparseable_token`` (a
field that is neither a number nor an NA spelling), ``ragged_row`` (a
delimited row whose field count disagrees with the file's),
``bad_column_index`` (a LibSVM index that is negative, non-integer, or
out of the fixed feature range), ``empty`` (a non-blank line with no
parseable fields at all).

The guard also owns the token helpers (:func:`feature_value`,
:func:`column_index`): tools/graftcheck's ``ingress`` rule family flags
raw ``float()``/``int()`` on file tokens outside this module, so every
conversion funnels through one place with one missing-value semantics
(NA/NaN/null/empty -> NaN, matching the reference's NA handling — the
bin mappers put NaN in bin 0 like BinMapper::ValueToBin).

Line numbers are 1-based physical file lines (header included), and the
guard dedupes by line number: the two-round loader classifies a sampled
bad line in round 1 and meets it again in round 2 — it must be
quarantined, counted, and budgeted exactly once for the preallocated
bins/labels to stay aligned.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Set, Tuple

from .. import obs
from ..utils import log
from ..utils.log import LightGBMError

#: classification vocabulary — the ``bad_rows_<reason>`` counter suffixes
REASONS = ("unparseable_token", "ragged_row", "bad_column_index", "empty")

POLICIES = ("fail_fast", "quarantine")

#: NA spellings mapped to missing (NaN), case-insensitive, plus the
#: empty field (reference CommonC::AtofPrecise NA handling)
NA_TOKENS = frozenset({"", "na", "nan", "null", "none"})

#: rows examined before the fractional budget arms — a 3-bad-of-5-rows
#: prefix of a million-row file must not abort a 0.1 budget
_FRACTION_GRACE_ROWS = 100

_QUARANTINE_SUFFIX = ".quarantine"


def quarantine_path_for(data_path: str) -> str:
    """Where rejected rows of ``data_path`` land."""
    return data_path + _QUARANTINE_SUFFIX


def feature_value(token: str) -> float:
    """One feature/label token -> float.  NA spellings and empty fields
    become NaN (missing — the bin mappers route NaN to bin 0 like the
    reference's BinMapper).  Raises ``ValueError`` on anything else so
    the caller's guard can classify the row; use this instead of a raw
    ``float()`` on file tokens (enforced by graftcheck's ``ingress``
    rules)."""
    t = token.strip()
    if t.lower() in NA_TOKENS:
        return float("nan")
    return float(t) 


#: hard ceiling on LibSVM column indices: the data layer is DENSE
#: feature-major (SURVEY.md §7), so a single absurd index would size the
#: whole matrix — a corrupt index must be classified, not malloc'd
MAX_COLUMN_INDEX = 1 << 20


def column_index(token: str) -> int:
    """One LibSVM column-index token -> non-negative int.  Raises
    ``ValueError`` on non-integers AND on negative indices — before this
    helper a negative index silently wrote into the *wrong feature*
    through Python's negative indexing — and on indices past
    :data:`MAX_COLUMN_INDEX` (one corrupt digit must not size a dense
    [N, 10^9] allocation)."""
    idx = int(token.strip())
    if idx < 0:
        raise ValueError(f"negative column index {idx}")
    if idx > MAX_COLUMN_INDEX:
        raise ValueError(
            f"column index {idx} exceeds the dense-layout ceiling "
            f"{MAX_COLUMN_INDEX}")
    return idx


class IngestGuard:
    """Per-file bad-row policy: classify, then fail fast or quarantine
    under an error budget.

    Parameters
    ----------
    path: the data file (diagnostics + quarantine sink location).
    policy: ``fail_fast`` | ``quarantine``.
    max_bad_rows: absolute quarantine budget (0 = no absolute cap).
    max_bad_row_fraction: relative budget over rows seen so far
        (0 = no fractional cap); armed after a small grace so tiny
        prefixes cannot abort a long file.
    sink: write the ``<path>.quarantine`` file (quarantine policy only).
    record: count/sink at all.  ``record=False`` is the *shadow* mode
        for a second pass over an already-guarded file (e.g. the
        continued-training re-read): identical skip decisions, zero
        double-counted ``bad_rows_*`` counters, no sink rewrite.
    """

    def __init__(self, path: str, policy: str = "fail_fast",
                 max_bad_rows: int = 0,
                 max_bad_row_fraction: float = 0.0,
                 sink: bool = True, record: bool = True):
        if policy not in POLICIES:
            raise LightGBMError(
                f"Unknown bad_data_policy {policy!r} "
                f"(expected one of {', '.join(POLICIES)})")
        self.path = str(path)
        self.policy = policy
        self.max_bad_rows = max(int(max_bad_rows), 0)
        self.max_bad_row_fraction = float(max_bad_row_fraction)
        self.record = bool(record)
        self._sink_enabled = bool(sink) and self.record
        self._sink = None
        self.bad_total = 0
        self.rows_seen = 0           # good + bad data rows examined
        self.by_reason: Dict[str, int] = {}
        self.records: List[Tuple[int, str, str]] = []  # (line, reason, detail)
        self._seen_lines: Set[int] = set()
        self._expected_fields: Optional[int] = None
        self._finished = False
        if self.policy == "quarantine" and self._sink_enabled:
            # a stale quarantine file from a previous load must not be
            # mistaken for this load's verdict
            try:
                os.unlink(self.quarantine_path)
            except OSError:
                pass

    # -- context manager: finish() on clean exit only ------------------
    def __enter__(self) -> "IngestGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.finish()
        else:
            self._close_sink()

    @property
    def quarantine_path(self) -> str:
        return quarantine_path_for(self.path)

    # -- field-count memory (ragged-row detection across chunks) -------
    def expect_fields(self, n: int) -> int:
        """Record (first call) or return the file's delimited field
        count, so ragged detection is consistent across parse chunks."""
        if self._expected_fields is None:
            self._expected_fields = int(n)
        return self._expected_fields

    # -- the classification entry point --------------------------------
    def bad_row(self, line_no: int, raw_line: str, reason: str,
                detail: str) -> bool:
        """One classified bad line.  ``fail_fast``: raises immediately.
        ``quarantine``: records, sinks, counts, budget-checks.  Returns
        True when the caller must SKIP the row (always, under
        quarantine); returns False when this line number was already
        accounted (two-round dedupe) — still skip, but silently."""
        if reason not in REASONS:
            raise ValueError(f"unknown bad-row reason {reason!r}")
        line_no = int(line_no)
        if self.policy == "fail_fast":
            raise LightGBMError(
                f"{self.path}:{line_no}: {reason}: {detail} "
                f"(bad_data_policy=fail_fast; set bad_data_policy="
                f"quarantine to skip bad rows under an error budget)")
        if line_no in self._seen_lines:
            return False
        self._seen_lines.add(line_no)
        self.bad_total += 1
        self.rows_seen += 1
        self.by_reason[reason] = self.by_reason.get(reason, 0) + 1
        self.records.append((line_no, reason, detail))
        if self.record:
            obs.inc("bad_rows_total")
            obs.inc(f"bad_rows_{reason}")
            self._sink_write(line_no, raw_line, reason, detail)
            if self.bad_total == 1:
                log.warning(
                    "%s:%d: %s: %s — quarantining to %s "
                    "(bad_data_policy=quarantine; further bad rows "
                    "logged to the sink only)",
                    self.path, line_no, reason, detail,
                    self.quarantine_path)
        self._check_budget(line_no, reason, detail)
        return True

    def good_rows(self, n: int) -> None:
        """Account ``n`` successfully parsed data rows (feeds the
        fractional budget's denominator)."""
        self.rows_seen += int(n)

    def rewind_good_rows(self, n: int) -> None:
        """Un-count ``n`` good rows that will be parsed AGAIN by a later
        pass over the same file (the two-round loader's round-1b sample
        lines reappear in round 2): bad rows dedupe by line number, good
        rows must not inflate the fractional budget's denominator."""
        self.rows_seen = max(self.rows_seen - int(n), 0)

    def is_quarantined(self, line_no: int) -> bool:
        return int(line_no) in self._seen_lines

    # -- budgets --------------------------------------------------------
    def _budget_error(self, line_no: int, reason: str, detail: str,
                      why: str) -> LightGBMError:
        return LightGBMError(
            f"{self.path}: bad-row budget exhausted ({why}) at line "
            f"{line_no} ({reason}: {detail}) — {self.bad_total} bad "
            f"row(s) so far, quarantined to {self.quarantine_path}. "
            f"The file is the problem, not the rows; raise "
            f"max_bad_rows/max_bad_row_fraction only if this much dirt "
            f"is expected.")

    def _check_budget(self, line_no: int, reason: str, detail: str) -> None:
        if self.max_bad_rows and self.bad_total > self.max_bad_rows:
            self._close_sink()
            raise self._budget_error(
                line_no, reason, detail,
                f"max_bad_rows={self.max_bad_rows}")
        frac = self.max_bad_row_fraction
        if frac > 0 and self.rows_seen >= _FRACTION_GRACE_ROWS \
                and self.bad_total > frac * self.rows_seen:
            self._close_sink()
            raise self._budget_error(
                line_no, reason, detail,
                f"max_bad_row_fraction={frac:g} over "
                f"{self.rows_seen} rows")

    def finish(self) -> None:
        """End-of-file bookkeeping: the fractional budget gets a final
        check (files shorter than the in-flight grace window), and the
        sink is flushed/closed.  Idempotent."""
        if self._finished:
            return
        self._finished = True
        frac = self.max_bad_row_fraction
        if frac > 0 and self.bad_total and self.rows_seen \
                and self.bad_total > frac * self.rows_seen:
            last = self.records[-1]
            self._close_sink()
            raise self._budget_error(
                last[0], last[1], last[2],
                f"max_bad_row_fraction={frac:g} over "
                f"{self.rows_seen} rows")
        self._close_sink()
        if self.bad_total and self.record:
            log.warning(
                "%s: quarantined %d bad row(s) (%s) -> %s",
                self.path, self.bad_total,
                ", ".join(f"{k}={v}"
                          for k, v in sorted(self.by_reason.items())),
                self.quarantine_path)

    # -- quarantine sink -------------------------------------------------
    def _sink_write(self, line_no: int, raw_line: str, reason: str,
                    detail: str) -> None:
        if not self._sink_enabled:
            return
        if self._sink is None:
            # guarded writer (utils/diskguard.py): a full disk disables
            # the quarantine SINK (warn-once + sink_write_errors_total)
            # while the in-memory accounting and error budgets keep
            # working — losing the sink file must not crash the load
            # (policy=None honors the run's sink_error_policy)
            from ..utils.diskguard import GuardedWriter
            self._sink = GuardedWriter(self.quarantine_path,
                                       sink="quarantine",
                                       policy=None, buffering=1)
            self._sink.write(
                "# lightgbm_tpu quarantine v1\n"
                f"# source: {self.path}\n"
                "# columns: line\treason\tdetail\traw\n")
        clean = raw_line.replace("\t", "\\t").replace("\n", "\\n")
        self._sink.write(f"{line_no}\t{reason}\t{detail}\t{clean}\n")

    def _close_sink(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


#: row-aligned companion files (metadata.cpp side-loading)
SIDE_FILE_SUFFIXES = (".weight", ".query", ".init")


def check_side_files_alignment(data_path: str, bad_total: int) -> None:
    """Refuse quarantine when row-aligned side files exist.  A
    ``.weight`` / ``.query`` / ``.init`` companion is positional
    against the DATA FILE's rows; once quarantine drops rows, every
    side value after the first dropped line would silently apply to
    the wrong row — exactly the corruption class this layer exists to
    eliminate, so it is a named refusal, not a crop."""
    if not bad_total:
        return
    present = [data_path + s for s in SIDE_FILE_SUFFIXES
               if os.path.exists(data_path + s)]
    if present:
        raise LightGBMError(
            f"{data_path}: {bad_total} row(s) were quarantined but "
            f"row-aligned side file(s) exist ({', '.join(present)}) — "
            f"their values cannot be re-aligned to the surviving rows. "
            f"Clean the data file (see {data_path}.quarantine) and "
            f"regenerate the side files, or use "
            f"bad_data_policy=fail_fast.")


def read_quarantine(path: str) -> List[Dict[str, object]]:
    """Parse a quarantine sink back into records (tests, tooling).
    ``path`` may be the data file or the sink itself."""
    if not path.endswith(_QUARANTINE_SUFFIX):
        path = quarantine_path_for(path)
    out: List[Dict[str, object]] = []
    with open(path, "r") as fh:
        for ln in fh:
            if ln.startswith("#") or not ln.strip():
                continue
            parts = ln.rstrip("\n").split("\t", 3)
            if len(parts) != 4:
                continue
            out.append({"line": int(parts[0]), "reason": parts[1],
                        "detail": parts[2], "raw": parts[3]})
    return out
