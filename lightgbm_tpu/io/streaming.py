"""Two-round (streaming) file loading.

Reference: dataset_loader.cpp:191-206 + config use_two_round_loading
(config.h:100, io_config two_round aliases).  The one-round path
materializes the full [N, F] float64 matrix before binning (~2.2 GB for
Higgs-10M) — the exact "single-host materialization wall" called out in
SURVEY §5.  Two-round loading never holds more than one text chunk and
the sample in memory:

  round 1a: stream the file once — count rows (and, for LibSVM, the max
            feature index, which late rows may raise; malformed LibSVM
            lines are classified HERE so a garbage index can never
            inflate the feature space);
  round 1b: stream again collecting ONLY the sampled lines (the sample
            indices are drawn exactly like the in-memory path:
            global row count + same seed -> the resulting mappers are
            bit-identical to BinnedDataset.from_matrix on the same file);
  round 2:  stream in chunks, parse each chunk, bin it straight into the
            preallocated uint8/uint16 bin matrix.

Peak memory: bins [used_F, N] (1 byte/cell) + chunk + sample, instead of
N * F * 8 bytes of floats.

Malformed input is contained (docs/FAULT_TOLERANCE.md §Data boundary):
every parse goes through the file's :class:`~.guard.IngestGuard`, which
dedupes by physical line number — a bad line sampled in round 1b and
met again in round 2 is quarantined, counted, and budgeted exactly
once, and the preallocated bins/labels are cropped to the clean row
count so they stay aligned.  File drift between rounds (a concurrent
appender/truncator changing the size or row count after round 1) is a
named ``LightGBMError``, not a silent mis-binning or a bare assert.

Chunks are parsed with the Python parser; the one-round path prefers the
native C++ loader whose fast atof can differ from float() by ~1 ulp, so
two-round and one-round bins may disagree on values that sit exactly on
a bin boundary (observed < 0.1% of cells on the reference examples;
mappers built from the same parser are bit-identical —
tests/test_streaming.py).
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

from ..utils import log
from ..utils.log import LightGBMError
from .binning import BinMapper
from .bundling import plan_bundles
from .dataset import (BinnedDataset, Metadata, _bins_dtype,
                      build_mappers_from_sample)
from .guard import (IngestGuard, check_side_files_alignment, column_index,
                    feature_value)
from .parser import (_BadLine, _parse_chunk,  # noqa: F401 (re-export)
                     detect_format)


def _numbered_data_lines(path: str, skip_header: bool
                         ) -> Iterator[Tuple[int, str]]:
    """Yield (1-based physical line number, newline-stripped line) for
    every non-blank data line, skipping the header.  Undecodable bytes
    are replaced so they reach the classifier instead of raising
    ``UnicodeDecodeError`` mid-stream."""
    with open(path, "r", errors="replace") as fh:
        lineno = 0
        if skip_header:
            fh.readline()
            lineno = 1
        for line in fh:
            lineno += 1
            line = line.rstrip("\r\n")
            if line.strip():
                yield lineno, line


def _data_lines(path: str, skip_header: bool):
    """Yield raw data lines (newline-stripped), skipping the header."""
    for _, line in _numbered_data_lines(path, skip_header):
        yield line


def _probe_format(path: str, has_header: bool) -> str:
    probe: List[str] = []
    for line in _data_lines(path, has_header):
        probe.append(line)
        if len(probe) >= 32:
            break
    return detect_format(probe)


def read_full_header_names(path: str) -> Tuple[List[str], str]:
    """(all header column names, detected format) from the first line."""
    fmt = _probe_format(path, True)
    with open(path, "r", errors="replace") as fh:
        first = fh.readline().rstrip("\r\n")
    delim = {"csv": ",", "tsv": "\t"}.get(fmt, "\t")
    return first.split(delim), fmt


def read_header_names(path: str, label_idx: int = 0) -> List[str]:
    """Feature names from the header line (label column removed)."""
    header, fmt = read_full_header_names(path)
    if label_idx >= 0 and fmt != "libsvm" and len(header) > label_idx:
        header = header[:label_idx] + header[label_idx + 1:]
    return header


def _scan_libsvm_max_col(line: str) -> int:
    """Max column index of one LibSVM line, with the SAME token
    validation as the real parse — raises :class:`_BadLine` on any
    malformed token so a corrupt row can never inflate the feature
    space (round 1a sizes the preallocated bin matrix from this)."""
    parts = line.split()
    start = 0
    if parts and ":" not in parts[0]:
        try:
            feature_value(parts[0])
        except ValueError:
            raise _BadLine("unparseable_token",
                           f"label token {parts[0]!r}")
        start = 1
    max_col = -1
    for tok in parts[start:]:
        col_s, sep, val_s = tok.partition(":")
        if not sep:
            raise _BadLine("unparseable_token",
                           f"token {tok!r} is not index:value")
        try:
            col = column_index(col_s)
        except ValueError:
            raise _BadLine("bad_column_index",
                           f"column index {col_s!r} in token {tok!r}")
        try:
            feature_value(val_s)
        except ValueError:
            raise _BadLine("unparseable_token",
                           f"value {val_s!r} in token {tok!r}")
        max_col = max(max_col, col)
    return max_col


def _drift_error(path: str, why: str) -> None:
    raise LightGBMError(
        f"Two-round loader: {path} changed between rounds ({why}) — a "
        f"concurrent writer is mutating the file; re-run the load "
        f"against a quiescent copy")


def load_file_two_round(path: str, *, has_header: bool = False,
                        label_idx: int = 0, max_bin: int = 255,
                        min_data_in_bin: int = 5, min_data_in_leaf: int = 100,
                        bin_construct_sample_cnt: int = 200000,
                        categorical_features: Sequence[int] = (),
                        ignore_features: Sequence[int] = (),
                        weight_idx: int = -1, group_idx: int = -1,
                        data_random_seed: int = 1,
                        reference: Optional[BinnedDataset] = None,
                        chunk_rows: int = 262144,
                        guard: Optional[IngestGuard] = None,
                        enable_bundle: bool = False,
                        max_conflict_rate: float = 0.0,
                        is_enable_sparse: bool = True,
                        ) -> BinnedDataset:
    """Stream-load ``path`` into a BinnedDataset without materializing the
    float matrix.  Identical output to parse_file + from_matrix (asserted
    by tests/test_streaming.py); with ``reference`` the file is binned
    with the reference's mappers (validation alignment).

    ``weight_idx`` / ``group_idx`` name in-data columns (feature-space
    indices, dataset_loader.cpp SetHeader) whose values stream into
    Metadata instead of features; callers put them in ignore_features.

    ``guard`` carries the bad-row policy (default: fail fast on the
    first malformed line, naming file:line + token)."""
    import numpy as np

    g = guard if guard is not None else IngestGuard(path)
    fmt = _probe_format(path, has_header)
    try:
        size_r1 = os.path.getsize(path)
    except OSError:
        size_r1 = -1

    # round 1a: row count (+ LibSVM feature count; skipped when the
    # reference already fixes the feature space).  LibSVM lines are
    # fully token-validated here — a malformed line is classified NOW
    # (fail fast / quarantine) instead of donating a garbage column
    # index to the matrix allocation.
    num_data = 0
    max_col = -1
    scan_cols = fmt == "libsvm" and reference is None
    delim = {"csv": ",", "tsv": "\t"}.get(fmt)
    width_seeded = False
    for lineno, line in _numbered_data_lines(path, has_header):
        if not width_seeded and delim is not None:
            # seed the ragged-row width from the file's FIRST data line
            # with any fields (the native loader's schema rule) —
            # round 1b parses a RANDOM sample, and seeding from
            # whichever line is sampled first would let one ragged line
            # invert classification for the whole file (and desync the
            # continued-training shadow guard, which always re-reads
            # from line 1)
            parts = line.split(delim)
            if any(p.strip() for p in parts):
                g.expect_fields(len(parts))
                width_seeded = True
        num_data += 1
        if scan_cols:
            try:
                max_col = max(max_col, _scan_libsvm_max_col(line))
            except _BadLine as bl:
                g.bad_row(lineno, line, bl.reason, bl.detail)
    if num_data == 0:
        log.fatal("Two-round loader: %s contains no data rows", path)

    if reference is not None:
        # mappers come from the reference: no sampling pass needed
        sample = None
        F = reference.num_total_features
    else:
        # round 1b: the sample — same indices as the in-memory path
        rng = np.random.RandomState(data_random_seed)
        if num_data > bin_construct_sample_cnt:
            sample_idx = np.sort(rng.choice(num_data,
                                            bin_construct_sample_cnt,
                                            replace=False))
        else:
            sample_idx = np.arange(num_data)
        wanted = np.zeros(num_data, bool)
        wanted[sample_idx] = True
        sample_lines: List[str] = []
        sample_nums: List[int] = []
        for i, (lineno, ln) in enumerate(
                _numbered_data_lines(path, has_header)):
            if i >= num_data:
                break       # late concurrent append: round 2 names it
            if wanted[i]:
                sample_lines.append(ln)
                sample_nums.append(lineno)
        num_features = (max_col + 1) if fmt == "libsvm" else None
        seen0, bad0 = g.rows_seen, g.bad_total
        _, sample = _parse_chunk(sample_lines, fmt, label_idx,
                                 num_features, guard=g,
                                 line_numbers=sample_nums)
        # the sampled GOOD lines will be parsed again in round 2: keep
        # their bad-row classifications (deduped by line number) but
        # give back their budget-denominator contribution, or a big
        # sample would make max_bad_row_fraction silently looser
        sample_good = (g.rows_seen - seen0) - (g.bad_total - bad0)
        g.rewind_good_rows(sample_good)
        F = sample.shape[1]

    ds = BinnedDataset()
    ds.num_total_features = F
    ds.max_bin = max_bin
    ds.label_idx = label_idx
    ds.feature_names = [f"Column_{i}" for i in range(F)]
    if has_header:
        header = read_header_names(path, label_idx)
        if len(header) == F:
            ds.feature_names = header

    if reference is not None:
        ds.num_total_features = reference.num_total_features
        ds.feature_names = list(reference.feature_names)
        ds.used_feature_map = list(reference.used_feature_map)
        ds.real_to_inner = reference.real_to_inner.copy()
        ds.mappers = reference.mappers
        ds.bundle_plan = reference.bundle_plan
    else:
        # trivial-feature filtering scales to the (estimated) CLEAN row
        # count: bad rows already classified never reach the bins, so
        # they must not count toward the filter denominator either
        per_real = build_mappers_from_sample(
            sample, max(num_data - g.bad_total, 1), max_bin=max_bin,
            min_data_in_bin=min_data_in_bin,
            min_data_in_leaf=min_data_in_leaf,
            categorical_features=set(int(c) for c in categorical_features),
            ignore_features=set(int(c) for c in ignore_features))
        ds.real_to_inner = np.full(F, -1, dtype=np.int64)
        used: List[int] = []
        mappers: List[BinMapper] = []
        for f, m in enumerate(per_real):
            if m is None or m.is_trivial:
                continue
            ds.real_to_inner[f] = len(used)
            used.append(f)
            mappers.append(m)
        ds.used_feature_map = used
        ds.mappers = mappers
        if not used:
            log.warning("All features are trivial; dataset has no usable "
                        "feature")
        # EFB over the round-1b sample — the SAME sample the in-memory
        # path would draw (identical seed + global row count), so both
        # loaders agree on bundles for identical files
        ds.bundle_plan = plan_bundles(
            sample, mappers, used,
            max_conflict_rate=max_conflict_rate, max_total_bin=max_bin,
            enable_bundle=enable_bundle, is_enable_sparse=is_enable_sparse)

    dtype = _bins_dtype(ds.mappers, ds.bundle_plan)
    num_columns = (ds.bundle_plan.num_columns
                   if ds.bundle_plan is not None
                   else len(ds.used_feature_map))
    ds.bins = np.zeros((num_columns, num_data), dtype=dtype)
    labels = np.zeros(num_data, np.float32)
    F_total = ds.num_total_features
    if weight_idx >= F_total:
        log.fatal("weight_column index %d out of range (file has %d "
                  "feature columns)", weight_idx, F_total)
    if group_idx >= F_total:
        log.fatal("group_column index %d out of range (file has %d "
                  "feature columns)", group_idx, F_total)
    weights = np.zeros(num_data, np.float64) if weight_idx >= 0 else None
    qids = np.zeros(num_data, np.float64) if group_idx >= 0 else None

    # drift gate: the file must not have changed since round 1 (size
    # now, exact row count re-verified during the round-2 stream)
    try:
        size_r2 = os.path.getsize(path)
    except OSError:
        size_r2 = -2
    if size_r2 != size_r1:
        _drift_error(path, f"size {size_r1} -> {size_r2} bytes")

    # round 2: chunked parse + bin
    off = 0
    lines_seen = 0
    buf: List[str] = []
    nums: List[int] = []
    nf = ds.num_total_features if fmt == "libsvm" else None

    def flush():
        nonlocal off, buf, nums
        if not buf:
            return
        lab, feats = _parse_chunk(buf, fmt, label_idx, nf, guard=g,
                                  line_numbers=nums)
        n = feats.shape[0]

        def _feature_bins(inner):
            f = ds.used_feature_map[inner]
            col = feats[:, f] if f < feats.shape[1] else \
                np.zeros(n, np.float64)
            return ds.mappers[inner].value_to_bin(col)

        if ds.bundle_plan is not None:
            ds.bins[:, off:off + n] = ds.bundle_plan.encode_columns(
                _feature_bins, n, dtype)
        else:
            for inner in range(len(ds.used_feature_map)):
                ds.bins[inner, off:off + n] = \
                    _feature_bins(inner).astype(dtype)
        labels[off:off + n] = lab.astype(np.float32)
        if weights is not None and weight_idx < feats.shape[1]:
            weights[off:off + n] = feats[:, weight_idx]
        if qids is not None and group_idx < feats.shape[1]:
            qids[off:off + n] = feats[:, group_idx]
        off += n
        buf = []
        nums = []

    for lineno, line in _numbered_data_lines(path, has_header):
        lines_seen += 1
        if lines_seen > num_data:
            break               # named below — not an assert, not a hang
        buf.append(line)
        nums.append(lineno)
        if len(buf) >= chunk_rows:
            flush()
    flush()

    if lines_seen != num_data:
        _drift_error(path, f"{num_data} data rows counted in round 1, "
                           f"{'>' if lines_seen > num_data else ''}"
                           f"{lines_seen} seen in round 2")
    if off + g.bad_total != num_data:
        _drift_error(path, f"{num_data} rows counted, {off} binned + "
                           f"{g.bad_total} quarantined")
    if off == 0:
        raise LightGBMError(
            f"Two-round loader: every row of {path} was quarantined "
            f"({g.bad_total} bad rows, see {g.quarantine_path}) — "
            f"no clean data to train on")

    if off < num_data:
        # quarantined rows: crop the preallocated arrays to the clean
        # count so bins/labels/metadata stay aligned
        ds.bins = np.ascontiguousarray(ds.bins[:, :off])
        labels = labels[:off]
        if weights is not None:
            weights = weights[:off]
        if qids is not None:
            qids = qids[:off]

    check_side_files_alignment(path, g.bad_total)
    ds.metadata = Metadata(off)
    ds.metadata.set_label(labels)
    ds.metadata.load_side_files(path)
    if weights is not None:
        ds.metadata.set_weights(weights)
    if qids is not None:
        from .column_roles import qid_to_query_sizes
        ds.metadata.set_query(qid_to_query_sizes(qids))
    g.finish()
    return ds
