"""Two-round (streaming) file loading.

Reference: dataset_loader.cpp:191-206 + config use_two_round_loading
(config.h:100, io_config two_round aliases).  The one-round path
materializes the full [N, F] float64 matrix before binning (~2.2 GB for
Higgs-10M) — the exact "single-host materialization wall" called out in
SURVEY §5.  Two-round loading never holds more than one text chunk and
the sample in memory:

  round 1a: stream the file once — count rows (and, for LibSVM, the max
            feature index, which late rows may raise);
  round 1b: stream again collecting ONLY the sampled lines (the sample
            indices are drawn exactly like the in-memory path:
            global row count + same seed -> the resulting mappers are
            bit-identical to BinnedDataset.from_matrix on the same file);
  round 2:  stream in chunks, parse each chunk, bin it straight into the
            preallocated uint8/uint16 bin matrix.

Peak memory: bins [used_F, N] (1 byte/cell) + chunk + sample, instead of
N * F * 8 bytes of floats.

Chunks are parsed with the Python parser; the one-round path prefers the
native C++ loader whose fast atof can differ from float() by ~1 ulp, so
two-round and one-round bins may disagree on values that sit exactly on
a bin boundary (observed < 0.1% of cells on the reference examples;
mappers built from the same parser are bit-identical —
tests/test_streaming.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils import log
from .binning import BinMapper
from .dataset import BinnedDataset, Metadata, build_mappers_from_sample
from .parser import _parse_chunk, detect_format  # noqa: F401 (re-export)


def _data_lines(path: str, skip_header: bool):
    """Yield raw data lines (newline-stripped), skipping the header."""
    with open(path, "r") as fh:
        if skip_header:
            fh.readline()
        for line in fh:
            line = line.rstrip("\r\n")
            if line.strip():
                yield line


def _probe_format(path: str, has_header: bool) -> str:
    probe: List[str] = []
    for line in _data_lines(path, has_header):
        probe.append(line)
        if len(probe) >= 32:
            break
    return detect_format(probe)


def read_full_header_names(path: str) -> Tuple[List[str], str]:
    """(all header column names, detected format) from the first line."""
    fmt = _probe_format(path, True)
    with open(path, "r") as fh:
        first = fh.readline().rstrip("\r\n")
    delim = {"csv": ",", "tsv": "\t"}.get(fmt, "\t")
    return first.split(delim), fmt


def read_header_names(path: str, label_idx: int = 0) -> List[str]:
    """Feature names from the header line (label column removed)."""
    header, fmt = read_full_header_names(path)
    if label_idx >= 0 and fmt != "libsvm" and len(header) > label_idx:
        header = header[:label_idx] + header[label_idx + 1:]
    return header




def load_file_two_round(path: str, *, has_header: bool = False,
                        label_idx: int = 0, max_bin: int = 255,
                        min_data_in_bin: int = 5, min_data_in_leaf: int = 100,
                        bin_construct_sample_cnt: int = 200000,
                        categorical_features: Sequence[int] = (),
                        ignore_features: Sequence[int] = (),
                        weight_idx: int = -1, group_idx: int = -1,
                        data_random_seed: int = 1,
                        reference: Optional[BinnedDataset] = None,
                        chunk_rows: int = 262144) -> BinnedDataset:
    """Stream-load ``path`` into a BinnedDataset without materializing the
    float matrix.  Identical output to parse_file + from_matrix (asserted
    by tests/test_streaming.py); with ``reference`` the file is binned
    with the reference's mappers (validation alignment).

    ``weight_idx`` / ``group_idx`` name in-data columns (feature-space
    indices, dataset_loader.cpp SetHeader) whose values stream into
    Metadata instead of features; callers put them in ignore_features."""
    fmt = _probe_format(path, has_header)

    # round 1a: row count (+ LibSVM feature count; skipped when the
    # reference already fixes the feature space)
    num_data = 0
    max_col = -1
    scan_cols = fmt == "libsvm" and reference is None
    for line in _data_lines(path, has_header):
        num_data += 1
        if scan_cols:
            parts = line.split()
            for tok in parts[1:] if ":" not in parts[0] else parts:
                max_col = max(max_col, int(tok.split(":", 1)[0]))
    if num_data == 0:
        log.fatal("Two-round loader: %s contains no data rows", path)

    if reference is not None:
        # mappers come from the reference: no sampling pass needed
        sample = None
        F = reference.num_total_features
    else:
        # round 1b: the sample — same indices as the in-memory path
        rng = np.random.RandomState(data_random_seed)
        if num_data > bin_construct_sample_cnt:
            sample_idx = np.sort(rng.choice(num_data,
                                            bin_construct_sample_cnt,
                                            replace=False))
        else:
            sample_idx = np.arange(num_data)
        wanted = np.zeros(num_data, bool)
        wanted[sample_idx] = True
        sample_lines = [ln for i, ln in
                        enumerate(_data_lines(path, has_header))
                        if wanted[i]]
        num_features = (max_col + 1) if fmt == "libsvm" else None
        _, sample = _parse_chunk(sample_lines, fmt, label_idx, num_features)
        F = sample.shape[1]

    ds = BinnedDataset()
    ds.num_total_features = F
    ds.max_bin = max_bin
    ds.label_idx = label_idx
    ds.feature_names = [f"Column_{i}" for i in range(F)]
    if has_header:
        header = read_header_names(path, label_idx)
        if len(header) == F:
            ds.feature_names = header

    if reference is not None:
        ds.num_total_features = reference.num_total_features
        ds.feature_names = list(reference.feature_names)
        ds.used_feature_map = list(reference.used_feature_map)
        ds.real_to_inner = reference.real_to_inner.copy()
        ds.mappers = reference.mappers
    else:
        per_real = build_mappers_from_sample(
            sample, num_data, max_bin=max_bin,
            min_data_in_bin=min_data_in_bin,
            min_data_in_leaf=min_data_in_leaf,
            categorical_features=set(int(c) for c in categorical_features),
            ignore_features=set(int(c) for c in ignore_features))
        ds.real_to_inner = np.full(F, -1, dtype=np.int64)
        used: List[int] = []
        mappers: List[BinMapper] = []
        for f, m in enumerate(per_real):
            if m is None or m.is_trivial:
                continue
            ds.real_to_inner[f] = len(used)
            used.append(f)
            mappers.append(m)
        ds.used_feature_map = used
        ds.mappers = mappers
        if not used:
            log.warning("All features are trivial; dataset has no usable "
                        "feature")

    dtype = np.uint8 if max([m.num_bin for m in ds.mappers] or [1]) <= 256 \
        else np.uint16
    ds.bins = np.zeros((len(ds.used_feature_map), num_data), dtype=dtype)
    labels = np.zeros(num_data, np.float32)
    F_total = ds.num_total_features
    if weight_idx >= F_total:
        log.fatal("weight_column index %d out of range (file has %d "
                  "feature columns)", weight_idx, F_total)
    if group_idx >= F_total:
        log.fatal("group_column index %d out of range (file has %d "
                  "feature columns)", group_idx, F_total)
    weights = np.zeros(num_data, np.float64) if weight_idx >= 0 else None
    qids = np.zeros(num_data, np.float64) if group_idx >= 0 else None

    # round 2: chunked parse + bin
    off = 0
    buf: List[str] = []
    nf = ds.num_total_features if fmt == "libsvm" else None

    def flush():
        nonlocal off, buf
        if not buf:
            return
        lab, feats = _parse_chunk(buf, fmt, label_idx, nf)
        n = feats.shape[0]
        for inner, f in enumerate(ds.used_feature_map):
            col = feats[:, f] if f < feats.shape[1] else \
                np.zeros(n, np.float64)
            ds.bins[inner, off:off + n] = \
                ds.mappers[inner].value_to_bin(col).astype(dtype)
        labels[off:off + n] = lab.astype(np.float32)
        if weights is not None and weight_idx < feats.shape[1]:
            weights[off:off + n] = feats[:, weight_idx]
        if qids is not None and group_idx < feats.shape[1]:
            qids[off:off + n] = feats[:, group_idx]
        off += n
        buf = []

    for line in _data_lines(path, has_header):
        buf.append(line)
        if len(buf) >= chunk_rows:
            flush()
    flush()
    assert off == num_data, (off, num_data)

    ds.metadata = Metadata(num_data)
    ds.metadata.set_label(labels)
    ds.metadata.load_side_files(path)
    if weights is not None:
        ds.metadata.set_weights(weights)
    if qids is not None:
        from .column_roles import qid_to_query_sizes
        ds.metadata.set_query(qid_to_query_sizes(qids))
    return ds
