"""Objective functions: gradients/hessians as vmapped XLA ops.

Each objective mirrors the exact math of the reference implementation
(src/objective/*.hpp, factory objective_function.cpp:9-29) but computes the
whole gradient vector in one fused jitted op instead of an OpenMP loop.

Score layout: [num_tree_per_iteration, N] (class-major like the reference's
score[k * num_data + i], multiclass_objective.hpp:32-36) — [1, N] for
single-model objectives.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import log
from ..io.dataset import Metadata


def _pad_rows(arr, num_rows: Optional[int]):
    """Pad a row-aligned [N] / [..., N] array with zeros up to num_rows
    (the shared row-bucket shape, utils/compile_cache.py bucket_rows).
    Zero labels/weights on pad rows are harmless: tree growth multiplies
    every padded row's gradients by its zero ``row_weight``."""
    if arr is None or num_rows is None:
        return arr
    n = arr.shape[-1]
    if num_rows <= n:
        return arr
    return jnp.pad(arr, [(0, 0)] * (arr.ndim - 1) + [(0, num_rows - n)])


class ObjectiveFunction:
    """Base: subclasses define the gradient math over score[K,N].

    Two call forms:

    - ``gradients(score)`` — the historical entry point, closing over
      this instance's dataset arrays (label, weights, ...).
    - ``gradients_with(arrays, score)`` — the FUNCTIONAL form: every
      per-dataset array travels as an argument (the pytree built by
      ``gradient_arrays()``), and the method reads only scalar
      parameters off ``self``.  This is what lets ``models/gbdt.py``
      share ONE jitted gradient/train-step program across boosters: two
      same-config runs hash to the same ``program_key()``, reuse the
      same traced program, and feed it their own arrays — zero
      recompiles on the second run instead of a fresh XLA program per
      booster (the labels used to be baked in as compile-time
      constants).

    Subclasses implement ``gradients_with`` and extend
    ``gradient_arrays``/``program_key`` when they carry extra state.
    """

    name = "none"
    num_tree_per_iteration = 1
    # sigmoid parameter recorded in the model file; <=0 means no transform
    sigmoid = -1.0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weights = (None if metadata.weights is None
                        else jnp.asarray(metadata.weights, jnp.float32))

    # -- functional gradient interface ---------------------------------
    def gradient_arrays(self, num_rows: Optional[int] = None) -> dict:
        """Pytree of the per-dataset arrays ``gradients_with`` consumes,
        row-aligned arrays zero-padded to ``num_rows`` (the shared row
        bucket) when given."""
        if self.uses_legacy_gradients():
            # legacy subclasses carry their state in closures; nothing
            # to thread through the argument pytree
            return {}
        return {"label": _pad_rows(self.label, num_rows),
                "weights": _pad_rows(self.weights, num_rows)}

    def uses_legacy_gradients(self) -> bool:
        """True for subclasses written against the pre-round-7 contract:
        they override ``gradients`` but not ``gradients_with``, so their
        gradient math closes over instance state and cannot join the
        shared-program registry (or the row-bucket padding, which would
        feed them padded scores their captured arrays don't match)."""
        cls = type(self)
        return (cls.gradients is not ObjectiveFunction.gradients
                and cls.gradients_with is ObjectiveFunction.gradients_with)

    def program_key(self) -> tuple:
        """Hashable fingerprint of everything ``gradients_with`` bakes
        into its traced program BESIDES the argument arrays (scalar
        hyper-parameters, data-derived scalars).  Two objectives with
        equal keys may share one jitted program."""
        if self.uses_legacy_gradients():
            # instance-specific closure state: never share across
            # instances (matches the pre-round-7 one-jit-per-booster
            # behavior for custom objective subclasses)
            return (type(self).__name__, id(self))
        return (type(self).__name__,)

    # instance attrs that hold per-dataset (O(num_data)) arrays; dropped
    # by program_holder so the process-wide jit registry retains only
    # scalars, not a dead dataset's device memory
    _ARRAY_ATTRS = ("label", "weights", "label_int", "label_pos_weights",
                    "query_classes", "discounts", "label_gain_j")

    def program_holder(self) -> "ObjectiveFunction":
        """The object the shared-program registry may retain for process
        lifetime: a shallow copy with every per-dataset array attribute
        removed (``gradients_with`` must read arrays from its argument
        pytree only — a stripped holder turns a violation into a loud
        AttributeError instead of silently pinning HBM).  Legacy
        subclasses (``uses_legacy_gradients``) are returned as-is; their
        id-based program_key already scopes them to this instance."""
        if self.uses_legacy_gradients():
            return self
        import copy
        holder = copy.copy(self)
        for attr in self._ARRAY_ATTRS:
            if attr in holder.__dict__:
                del holder.__dict__[attr]
        return holder

    def gradients_with(self, arrays: dict, score: jax.Array
                       ) -> Tuple[jax.Array, jax.Array]:
        if self.uses_legacy_gradients():
            # pre-round-7 custom subclass: route through its gradients()
            # (closure state and all; arrays argument unused)
            return self.gradients(score)
        raise NotImplementedError

    def gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        return self.gradients_with(self.gradient_arrays(), score)

    @staticmethod
    def _apply_weight(arrays, grad, hess):
        w = arrays.get("weights")
        if w is None:
            return grad, hess
        return grad * w, hess * w

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        """Raw score -> prediction transform (GBDT::Predict, gbdt.cpp:799-815)."""
        return score

    def boost_from_average(self) -> float:
        return 0.0


class RegressionL2Loss(ObjectiveFunction):
    """g = score - label, h = 1 (regression_objective.hpp:25-53)."""
    name = "regression"

    def gradients_with(self, arrays, score):
        g = score[0] - arrays["label"]
        h = jnp.ones_like(g)
        g, h = self._apply_weight(arrays, g, h)
        return g[None], h[None]


def _gaussian_hessian(score, label, grad, eta, weight):
    """Common::ApproximateHessianWithGaussian (common.h:416-425)."""
    diff = score - label
    x = jnp.abs(diff)
    a = 2.0 * jnp.abs(grad) * weight
    c = jnp.maximum((jnp.abs(score) + jnp.abs(label)) * eta, 1.0e-10)
    return weight * jnp.exp(-x * x / (2.0 * c * c)) * a / (c * jnp.sqrt(2 * jnp.pi))


class RegressionL1Loss(ObjectiveFunction):
    """g = ±weight, h = Gaussian approx (regression_objective.hpp:58-113)."""
    name = "regression_l1"

    def __init__(self, config):
        self.eta = float(config.gaussian_eta)

    def program_key(self):
        return (type(self).__name__, self.eta)

    def gradients_with(self, arrays, score):
        s = score[0]
        label, weights = arrays["label"], arrays["weights"]
        w = weights if weights is not None else jnp.ones_like(s)
        diff = s - label
        g = jnp.where(diff >= 0.0, w, -w)
        h = _gaussian_hessian(s, label, g, self.eta, w)
        return g[None], h[None]


class RegressionHuberLoss(ObjectiveFunction):
    """L2 within delta, clipped gradient + Gaussian hessian outside
    (regression_objective.hpp:115-180)."""
    name = "huber"

    def __init__(self, config):
        self.delta = float(config.huber_delta)
        self.eta = float(config.gaussian_eta)

    def program_key(self):
        return (type(self).__name__, self.delta, self.eta)

    def gradients_with(self, arrays, score):
        s = score[0]
        label, weights = arrays["label"], arrays["weights"]
        w = weights if weights is not None else jnp.ones_like(s)
        diff = s - label
        inside = jnp.abs(diff) <= self.delta
        g_in = diff * w
        g_out = jnp.where(diff >= 0.0, self.delta * w, -self.delta * w)
        g = jnp.where(inside, g_in, g_out)
        h_out = _gaussian_hessian(s, label, g_out, self.eta, w)
        h = jnp.where(inside, w, h_out)
        return g[None], h[None]


class RegressionFairLoss(ObjectiveFunction):
    """g = c*x/(|x|+c), h = c^2/(|x|+c)^2 (regression_objective.hpp:182-235)."""
    name = "fair"

    def __init__(self, config):
        self.c = float(config.fair_c)

    def program_key(self):
        return (type(self).__name__, self.c)

    def gradients_with(self, arrays, score):
        x = score[0] - arrays["label"]
        c = self.c
        g = c * x / (jnp.abs(x) + c)
        h = c * c / ((jnp.abs(x) + c) ** 2)
        g, h = self._apply_weight(arrays, g, h)
        return g[None], h[None]


class RegressionPoissonLoss(ObjectiveFunction):
    """g = score - label, h = score + max_delta_step at this pin
    (regression_objective.hpp:237-289)."""
    name = "poisson"

    def __init__(self, config):
        self.max_delta_step = float(config.poisson_max_delta_step)

    def program_key(self):
        return (type(self).__name__, self.max_delta_step)

    def gradients_with(self, arrays, score):
        s = score[0]
        g = s - arrays["label"]
        h = s + self.max_delta_step
        g, h = self._apply_weight(arrays, g, h)
        return g[None], h[None]


class BinaryLogloss(ObjectiveFunction):
    """label -> ±1; response = -l*sigma/(1+exp(l*sigma*s)); class-imbalance
    reweighting via is_unbalance / scale_pos_weight
    (binary_objective.hpp:13-120)."""
    name = "binary"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = np.asarray(metadata.label)
        cnt_pos = int((label > 0).sum())
        cnt_neg = int(num_data - cnt_pos)
        log.info("Number of positive: %d, number of negative: %d",
                 cnt_pos, cnt_neg)
        if cnt_pos == 0 or cnt_neg == 0:
            log.fatal("Training data only contains one class")
        w_neg, w_pos = 1.0, 1.0
        if self.is_unbalance:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.label_weight_pos = w_pos
        self.label_weight_neg = w_neg

    def program_key(self):
        # label_weight_pos/neg are data-derived SCALARS (class counts):
        # they are baked into the traced program, so they must key it
        return (type(self).__name__, self.sigmoid,
                float(self.label_weight_pos), float(self.label_weight_neg))

    def gradients_with(self, arrays, score):
        s = score[0]
        is_pos = arrays["label"] > 0
        lbl = jnp.where(is_pos, 1.0, -1.0)
        lw = jnp.where(is_pos, self.label_weight_pos, self.label_weight_neg)
        sig = self.sigmoid
        response = -lbl * sig / (1.0 + jnp.exp(lbl * sig * s))
        abs_resp = jnp.abs(response)
        g = response * lw
        h = abs_resp * (sig - abs_resp) * lw
        g, h = self._apply_weight(arrays, g, h)
        return g[None], h[None]

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))


class MulticlassLogloss(ObjectiveFunction):
    """Softmax over class-major scores; g = p - 1{y=k}, h = 2p(1-p); optional
    per-class unbalance weights (multiclass_objective.hpp:13-120)."""
    name = "multiclass"

    def __init__(self, config):
        self.num_class = int(config.num_class)
        self.num_tree_per_iteration = self.num_class
        self.is_unbalance = bool(config.is_unbalance)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = np.asarray(metadata.label).astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class:
            log.fatal("Label must be in [0, %d)", self.num_class)
        self.label_int = jnp.asarray(label_int)
        pos_w = np.ones(self.num_class, np.float32)
        if self.is_unbalance:
            cnts = np.bincount(label_int, minlength=self.num_class)
            pos_w = ((num_data - cnts) / np.maximum(cnts, 1)).astype(np.float32)
        self.label_pos_weights = jnp.asarray(pos_w)

    def gradient_arrays(self, num_rows=None):
        arrays = super().gradient_arrays(num_rows)
        arrays["label_int"] = _pad_rows(self.label_int, num_rows)
        arrays["label_pos_weights"] = self.label_pos_weights
        return arrays

    def program_key(self):
        return (type(self).__name__, self.num_class)

    def gradients_with(self, arrays, score):
        # score: [K, N]
        p = jax.nn.softmax(score, axis=0)
        onehot = (jnp.arange(self.num_class, dtype=jnp.int32)[:, None]
                  == arrays["label_int"][None, :])
        pw = arrays["label_pos_weights"][:, None]
        g = jnp.where(onehot, (p - 1.0) * pw, p)
        h = jnp.where(onehot, 2.0 * p * (1.0 - p) * pw, 2.0 * p * (1.0 - p))
        weights = arrays["weights"]
        if weights is not None:
            g = g * weights[None, :]
            h = h * weights[None, :]
        return g, h

    def convert_output(self, score):
        e = np.exp(score - score.max(axis=0, keepdims=True))
        return e / e.sum(axis=0, keepdims=True)


def default_label_gain(size: int = 31):
    """2^i - 1 (config.cpp label_gain default)."""
    return [float((1 << i) - 1) for i in range(size)]


class LambdarankNDCG(ObjectiveFunction):
    """Per-query pairwise LambdaRank with NDCG weighting
    (rank_objective.hpp:19-228).

    TPU formulation: queries are bucketed by power-of-two size class (a
    query of 100 docs pads to 128, not to the global max — on MSLR-scale
    data where the longest query is ~10x the mean, per-class padding keeps
    pairwise work within ~4x of optimal instead of ~100x).  Within a class
    the pairwise lambda matrix [P, P] is computed per query with masking,
    queries processed in blocks via lax.map; one scatter-add per class
    accumulates into the row-order gradient.  The reference's 1M-entry
    sigmoid lookup table (rank_objective.hpp:177-190) is replaced by the
    exact sigmoid 2/(1+exp(2*sigma*d)) it approximates.
    """
    name = "lambdarank"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        gains = list(config.label_gain) or default_label_gain()
        self.label_gain = np.asarray(gains, np.float64)
        self.optimize_pos_at = int(config.max_position)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(qb) - 1
        sizes = np.diff(qb)
        M = int(sizes.max())
        label = np.asarray(metadata.label)
        # discounts must cover the LARGEST padded class, not just M
        disc_len = 16
        while disc_len < M:
            disc_len *= 2
        discounts = 1.0 / np.log2(np.arange(disc_len) + 2.0)
        self.discounts = jnp.asarray(discounts, jnp.float32)
        self.label_gain_j = jnp.asarray(self.label_gain, jnp.float32)

        # bucket queries by pow-2 padded size
        def pad_class(n):
            p = 16
            while p < n:
                p *= 2
            return p

        buckets = {}
        for q in range(self.num_queries):
            buckets.setdefault(pad_class(int(sizes[q])), []).append(q)

        self.query_classes = []
        for P, qlist in sorted(buckets.items()):
            Qc = len(qlist)
            doc_idx = np.zeros((Qc, P), np.int32)
            doc_valid = np.zeros((Qc, P), bool)
            inv_max_dcg = np.zeros(Qc, np.float64)
            for i, q in enumerate(qlist):
                cnt = int(sizes[q])
                doc_idx[i, :cnt] = np.arange(qb[q], qb[q + 1])
                doc_valid[i, :cnt] = True
                # inverse max DCG per query (rank_objective.hpp:54-64)
                lbl = np.sort(label[qb[q]:qb[q + 1]])[::-1]
                k = min(self.optimize_pos_at, cnt)
                dcg = (self.label_gain[lbl[:k].astype(int)]
                       * discounts[:k]).sum()
                inv_max_dcg[i] = 1.0 / dcg if dcg > 0 else 0.0
            padded_label = np.where(doc_valid, label[doc_idx], 0)
            self.query_classes.append({
                "P": P,
                "doc_idx": jnp.asarray(doc_idx),
                "doc_valid": jnp.asarray(doc_valid),
                "label": jnp.asarray(padded_label.astype(np.int32)),
                "inv_max_dcg": jnp.asarray(inv_max_dcg, jnp.float32),
            })

    def gradient_arrays(self, num_rows=None):
        arrays = super().gradient_arrays(num_rows)
        arrays["discounts"] = self.discounts
        arrays["label_gain_j"] = self.label_gain_j
        # per-size-class query tables WITHOUT the static pad size P —
        # gradients_with recovers it from doc_idx.shape (static under
        # trace), so the whole bundle travels as a plain arg pytree
        arrays["classes"] = tuple(
            {k: v for k, v in cls.items() if k != "P"}
            for cls in self.query_classes)
        return arrays

    def program_key(self):
        return (type(self).__name__, self.sigmoid, self.optimize_pos_at)

    def gradients_with(self, arrays, score):
        s = jnp.asarray(score)[0]
        g = jnp.zeros_like(s)
        h = jnp.zeros_like(s)
        for cls in arrays["classes"]:
            g, h = self._class_gradients(arrays, s, cls, g, h)
        weights = arrays["weights"]
        if weights is not None:
            g = g * weights
            h = h * weights
        return g[None], h[None]

    def _class_gradients(self, arrays, s, cls, g, h):
        M = cls["doc_idx"].shape[1]

        def one_query(args):
            doc_idx, valid, labels, inv_max_dcg = args
            sc = jnp.where(valid, s[doc_idx], -jnp.inf)
            order = jnp.argsort(-sc)  # descending; invalid sink to the end
            sc_sorted = sc[order]
            lbl_sorted = labels[order]
            valid_sorted = valid[order]
            gain_sorted = arrays["label_gain_j"][lbl_sorted]
            disc = arrays["discounts"][:M]
            n_valid = valid.sum()
            best = sc_sorted[0]
            worst = sc_sorted[jnp.maximum(n_valid - 1, 0)]
            # pairwise [i=high, j=low] in sorted positions
            delta = sc_sorted[:, None] - sc_sorted[None, :]
            dcg_gap = gain_sorted[:, None] - gain_sorted[None, :]
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
            norm = jnp.where(best != worst, 0.01 + jnp.abs(delta), 1.0)
            delta_ndcg = delta_ndcg / norm
            p = 2.0 / (1.0 + jnp.exp(2.0 * delta * self.sigmoid))
            lam = -p * delta_ndcg
            hes = p * (2.0 - p) * 2.0 * delta_ndcg
            pair_ok = ((lbl_sorted[:, None] > lbl_sorted[None, :])
                       & valid_sorted[:, None] & valid_sorted[None, :])
            lam = jnp.where(pair_ok, lam, 0.0)
            hes = jnp.where(pair_ok, hes, 0.0)
            g_sorted = lam.sum(axis=1) - lam.sum(axis=0)
            h_sorted = hes.sum(axis=1) + hes.sum(axis=0)
            # unsort back to query-document order
            g_q = jnp.zeros(M, jnp.float32).at[order].set(g_sorted)
            h_q = jnp.zeros(M, jnp.float32).at[order].set(h_sorted)
            return g_q, h_q

        g_pad, h_pad = jax.lax.map(
            one_query,
            (cls["doc_idx"], cls["doc_valid"], cls["label"],
             cls["inv_max_dcg"]),
            batch_size=max(1, 65536 // max(M, 1)))
        flat_idx = cls["doc_idx"].reshape(-1)
        flat_valid = cls["doc_valid"].reshape(-1)
        g = g.at[flat_idx].add(jnp.where(flat_valid, g_pad.reshape(-1), 0.0))
        h = h.at[flat_idx].add(jnp.where(flat_valid, h_pad.reshape(-1), 0.0))
        return g, h


_OBJECTIVES = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassLogloss,
    "lambdarank": LambdarankNDCG,
}


class NoneObjective(ObjectiveFunction):
    """Placeholder for python-side custom objectives (fobj): gradients come
    from the user callback via Booster.update(fobj=...); this only carries
    num_tree_per_iteration and an identity output transform (the reference
    trains with a NULL objective through LGBM_BoosterUpdateOneIterCustom,
    c_api.h:372-388)."""

    name = "none"

    def __init__(self, config=None):
        self.num_class = getattr(config, "num_class", 1) if config else 1
        self.num_tree_per_iteration = max(self.num_class, 1)

    def init(self, metadata, num_data):
        pass

    def gradient_arrays(self, num_rows=None):
        return {}

    def gradients_with(self, arrays, score):
        raise RuntimeError(
            "objective=none requires a custom fobj passed to train()/update()")

    def convert_output(self, score):
        return score


_OBJECTIVES["none"] = NoneObjective


def create_objective(config) -> ObjectiveFunction:
    """Factory (objective_function.cpp:9-29)."""
    name = config.objective
    if name not in _OBJECTIVES:
        log.fatal("Unknown objective type name: %s", name)
    cls = _OBJECTIVES[name]
    try:
        return cls(config)
    except TypeError:
        return cls()
