"""Objective functions: gradients/hessians as vmapped XLA ops.

Each objective mirrors the exact math of the reference implementation
(src/objective/*.hpp, factory objective_function.cpp:9-29) but computes the
whole gradient vector in one fused jitted op instead of an OpenMP loop.

Score layout: [num_tree_per_iteration, N] (class-major like the reference's
score[k * num_data + i], multiclass_objective.hpp:32-36) — [1, N] for
single-model objectives.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import log
from ..io.dataset import Metadata


class ObjectiveFunction:
    """Base: subclasses define gradients(score[K,N]) -> (grad[K,N], hess[K,N])."""

    name = "none"
    num_tree_per_iteration = 1
    # sigmoid parameter recorded in the model file; <=0 means no transform
    sigmoid = -1.0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weights = (None if metadata.weights is None
                        else jnp.asarray(metadata.weights, jnp.float32))

    def gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def _apply_weight(self, grad, hess):
        if self.weights is None:
            return grad, hess
        return grad * self.weights, hess * self.weights

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        """Raw score -> prediction transform (GBDT::Predict, gbdt.cpp:799-815)."""
        return score

    def boost_from_average(self) -> float:
        return 0.0


class RegressionL2Loss(ObjectiveFunction):
    """g = score - label, h = 1 (regression_objective.hpp:25-53)."""
    name = "regression"

    def gradients(self, score):
        g = score[0] - self.label
        h = jnp.ones_like(g)
        g, h = self._apply_weight(g, h)
        return g[None], h[None]


def _gaussian_hessian(score, label, grad, eta, weight):
    """Common::ApproximateHessianWithGaussian (common.h:416-425)."""
    diff = score - label
    x = jnp.abs(diff)
    a = 2.0 * jnp.abs(grad) * weight
    c = jnp.maximum((jnp.abs(score) + jnp.abs(label)) * eta, 1.0e-10)
    return weight * jnp.exp(-x * x / (2.0 * c * c)) * a / (c * jnp.sqrt(2 * jnp.pi))


class RegressionL1Loss(ObjectiveFunction):
    """g = ±weight, h = Gaussian approx (regression_objective.hpp:58-113)."""
    name = "regression_l1"

    def __init__(self, config):
        self.eta = float(config.gaussian_eta)

    def gradients(self, score):
        s = score[0]
        w = self.weights if self.weights is not None else jnp.ones_like(s)
        diff = s - self.label
        g = jnp.where(diff >= 0.0, w, -w)
        h = _gaussian_hessian(s, self.label, g, self.eta, w)
        return g[None], h[None]


class RegressionHuberLoss(ObjectiveFunction):
    """L2 within delta, clipped gradient + Gaussian hessian outside
    (regression_objective.hpp:115-180)."""
    name = "huber"

    def __init__(self, config):
        self.delta = float(config.huber_delta)
        self.eta = float(config.gaussian_eta)

    def gradients(self, score):
        s = score[0]
        w = self.weights if self.weights is not None else jnp.ones_like(s)
        diff = s - self.label
        inside = jnp.abs(diff) <= self.delta
        g_in = diff * w
        g_out = jnp.where(diff >= 0.0, self.delta * w, -self.delta * w)
        g = jnp.where(inside, g_in, g_out)
        h_out = _gaussian_hessian(s, self.label, g_out, self.eta, w)
        h = jnp.where(inside, w, h_out)
        return g[None], h[None]


class RegressionFairLoss(ObjectiveFunction):
    """g = c*x/(|x|+c), h = c^2/(|x|+c)^2 (regression_objective.hpp:182-235)."""
    name = "fair"

    def __init__(self, config):
        self.c = float(config.fair_c)

    def gradients(self, score):
        x = score[0] - self.label
        c = self.c
        g = c * x / (jnp.abs(x) + c)
        h = c * c / ((jnp.abs(x) + c) ** 2)
        g, h = self._apply_weight(g, h)
        return g[None], h[None]


class RegressionPoissonLoss(ObjectiveFunction):
    """g = score - label, h = score + max_delta_step at this pin
    (regression_objective.hpp:237-289)."""
    name = "poisson"

    def __init__(self, config):
        self.max_delta_step = float(config.poisson_max_delta_step)

    def gradients(self, score):
        s = score[0]
        g = s - self.label
        h = s + self.max_delta_step
        g, h = self._apply_weight(g, h)
        return g[None], h[None]


class BinaryLogloss(ObjectiveFunction):
    """label -> ±1; response = -l*sigma/(1+exp(l*sigma*s)); class-imbalance
    reweighting via is_unbalance / scale_pos_weight
    (binary_objective.hpp:13-120)."""
    name = "binary"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = np.asarray(metadata.label)
        cnt_pos = int((label > 0).sum())
        cnt_neg = int(num_data - cnt_pos)
        log.info("Number of positive: %d, number of negative: %d",
                 cnt_pos, cnt_neg)
        if cnt_pos == 0 or cnt_neg == 0:
            log.fatal("Training data only contains one class")
        w_neg, w_pos = 1.0, 1.0
        if self.is_unbalance:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.label_weight_pos = w_pos
        self.label_weight_neg = w_neg

    def gradients(self, score):
        s = score[0]
        is_pos = self.label > 0
        lbl = jnp.where(is_pos, 1.0, -1.0)
        lw = jnp.where(is_pos, self.label_weight_pos, self.label_weight_neg)
        sig = self.sigmoid
        response = -lbl * sig / (1.0 + jnp.exp(lbl * sig * s))
        abs_resp = jnp.abs(response)
        g = response * lw
        h = abs_resp * (sig - abs_resp) * lw
        g, h = self._apply_weight(g, h)
        return g[None], h[None]

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))


class MulticlassLogloss(ObjectiveFunction):
    """Softmax over class-major scores; g = p - 1{y=k}, h = 2p(1-p); optional
    per-class unbalance weights (multiclass_objective.hpp:13-120)."""
    name = "multiclass"

    def __init__(self, config):
        self.num_class = int(config.num_class)
        self.num_tree_per_iteration = self.num_class
        self.is_unbalance = bool(config.is_unbalance)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = np.asarray(metadata.label).astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class:
            log.fatal("Label must be in [0, %d)", self.num_class)
        self.label_int = jnp.asarray(label_int)
        pos_w = np.ones(self.num_class, np.float32)
        if self.is_unbalance:
            cnts = np.bincount(label_int, minlength=self.num_class)
            pos_w = ((num_data - cnts) / np.maximum(cnts, 1)).astype(np.float32)
        self.label_pos_weights = jnp.asarray(pos_w)

    def gradients(self, score):
        # score: [K, N]
        p = jax.nn.softmax(score, axis=0)
        onehot = (jnp.arange(self.num_class, dtype=jnp.int32)[:, None]
                  == self.label_int[None, :])
        pw = self.label_pos_weights[:, None]
        g = jnp.where(onehot, (p - 1.0) * pw, p)
        h = jnp.where(onehot, 2.0 * p * (1.0 - p) * pw, 2.0 * p * (1.0 - p))
        if self.weights is not None:
            g = g * self.weights[None, :]
            h = h * self.weights[None, :]
        return g, h

    def convert_output(self, score):
        e = np.exp(score - score.max(axis=0, keepdims=True))
        return e / e.sum(axis=0, keepdims=True)


def default_label_gain(size: int = 31):
    """2^i - 1 (config.cpp label_gain default)."""
    return [float((1 << i) - 1) for i in range(size)]


class LambdarankNDCG(ObjectiveFunction):
    """Per-query pairwise LambdaRank with NDCG weighting
    (rank_objective.hpp:19-228).

    TPU formulation: queries are bucketed by power-of-two size class (a
    query of 100 docs pads to 128, not to the global max — on MSLR-scale
    data where the longest query is ~10x the mean, per-class padding keeps
    pairwise work within ~4x of optimal instead of ~100x).  Within a class
    the pairwise lambda matrix [P, P] is computed per query with masking,
    queries processed in blocks via lax.map; one scatter-add per class
    accumulates into the row-order gradient.  The reference's 1M-entry
    sigmoid lookup table (rank_objective.hpp:177-190) is replaced by the
    exact sigmoid 2/(1+exp(2*sigma*d)) it approximates.
    """
    name = "lambdarank"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        gains = list(config.label_gain) or default_label_gain()
        self.label_gain = np.asarray(gains, np.float64)
        self.optimize_pos_at = int(config.max_position)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(qb) - 1
        sizes = np.diff(qb)
        M = int(sizes.max())
        label = np.asarray(metadata.label)
        # discounts must cover the LARGEST padded class, not just M
        disc_len = 16
        while disc_len < M:
            disc_len *= 2
        discounts = 1.0 / np.log2(np.arange(disc_len) + 2.0)
        self.discounts = jnp.asarray(discounts, jnp.float32)
        self.label_gain_j = jnp.asarray(self.label_gain, jnp.float32)

        # bucket queries by pow-2 padded size
        def pad_class(n):
            p = 16
            while p < n:
                p *= 2
            return p

        buckets = {}
        for q in range(self.num_queries):
            buckets.setdefault(pad_class(int(sizes[q])), []).append(q)

        self.query_classes = []
        for P, qlist in sorted(buckets.items()):
            Qc = len(qlist)
            doc_idx = np.zeros((Qc, P), np.int32)
            doc_valid = np.zeros((Qc, P), bool)
            inv_max_dcg = np.zeros(Qc, np.float64)
            for i, q in enumerate(qlist):
                cnt = int(sizes[q])
                doc_idx[i, :cnt] = np.arange(qb[q], qb[q + 1])
                doc_valid[i, :cnt] = True
                # inverse max DCG per query (rank_objective.hpp:54-64)
                lbl = np.sort(label[qb[q]:qb[q + 1]])[::-1]
                k = min(self.optimize_pos_at, cnt)
                dcg = (self.label_gain[lbl[:k].astype(int)]
                       * discounts[:k]).sum()
                inv_max_dcg[i] = 1.0 / dcg if dcg > 0 else 0.0
            padded_label = np.where(doc_valid, label[doc_idx], 0)
            self.query_classes.append({
                "P": P,
                "doc_idx": jnp.asarray(doc_idx),
                "doc_valid": jnp.asarray(doc_valid),
                "label": jnp.asarray(padded_label.astype(np.int32)),
                "inv_max_dcg": jnp.asarray(inv_max_dcg, jnp.float32),
            })

    def gradients(self, score):
        s = jnp.asarray(score)[0]
        g = jnp.zeros_like(s)
        h = jnp.zeros_like(s)
        for cls in self.query_classes:
            g, h = self._class_gradients(s, cls, g, h)
        if self.weights is not None:
            g = g * self.weights
            h = h * self.weights
        return g[None], h[None]

    def _class_gradients(self, s, cls, g, h):
        M = cls["P"]

        def one_query(args):
            doc_idx, valid, labels, inv_max_dcg = args
            sc = jnp.where(valid, s[doc_idx], -jnp.inf)
            order = jnp.argsort(-sc)  # descending; invalid sink to the end
            sc_sorted = sc[order]
            lbl_sorted = labels[order]
            valid_sorted = valid[order]
            gain_sorted = self.label_gain_j[lbl_sorted]
            disc = self.discounts[:M]
            n_valid = valid.sum()
            best = sc_sorted[0]
            worst = sc_sorted[jnp.maximum(n_valid - 1, 0)]
            # pairwise [i=high, j=low] in sorted positions
            delta = sc_sorted[:, None] - sc_sorted[None, :]
            dcg_gap = gain_sorted[:, None] - gain_sorted[None, :]
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
            norm = jnp.where(best != worst, 0.01 + jnp.abs(delta), 1.0)
            delta_ndcg = delta_ndcg / norm
            p = 2.0 / (1.0 + jnp.exp(2.0 * delta * self.sigmoid))
            lam = -p * delta_ndcg
            hes = p * (2.0 - p) * 2.0 * delta_ndcg
            pair_ok = ((lbl_sorted[:, None] > lbl_sorted[None, :])
                       & valid_sorted[:, None] & valid_sorted[None, :])
            lam = jnp.where(pair_ok, lam, 0.0)
            hes = jnp.where(pair_ok, hes, 0.0)
            g_sorted = lam.sum(axis=1) - lam.sum(axis=0)
            h_sorted = hes.sum(axis=1) + hes.sum(axis=0)
            # unsort back to query-document order
            g_q = jnp.zeros(M, jnp.float32).at[order].set(g_sorted)
            h_q = jnp.zeros(M, jnp.float32).at[order].set(h_sorted)
            return g_q, h_q

        g_pad, h_pad = jax.lax.map(
            one_query,
            (cls["doc_idx"], cls["doc_valid"], cls["label"],
             cls["inv_max_dcg"]),
            batch_size=max(1, 65536 // max(M, 1)))
        flat_idx = cls["doc_idx"].reshape(-1)
        flat_valid = cls["doc_valid"].reshape(-1)
        g = g.at[flat_idx].add(jnp.where(flat_valid, g_pad.reshape(-1), 0.0))
        h = h.at[flat_idx].add(jnp.where(flat_valid, h_pad.reshape(-1), 0.0))
        return g, h


_OBJECTIVES = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassLogloss,
    "lambdarank": LambdarankNDCG,
}


class NoneObjective(ObjectiveFunction):
    """Placeholder for python-side custom objectives (fobj): gradients come
    from the user callback via Booster.update(fobj=...); this only carries
    num_tree_per_iteration and an identity output transform (the reference
    trains with a NULL objective through LGBM_BoosterUpdateOneIterCustom,
    c_api.h:372-388)."""

    name = "none"

    def __init__(self, config=None):
        self.num_class = getattr(config, "num_class", 1) if config else 1
        self.num_tree_per_iteration = max(self.num_class, 1)

    def init(self, metadata, num_data):
        pass

    def gradients(self, score):
        raise RuntimeError(
            "objective=none requires a custom fobj passed to train()/update()")

    def convert_output(self, score):
        return score


_OBJECTIVES["none"] = NoneObjective


def create_objective(config) -> ObjectiveFunction:
    """Factory (objective_function.cpp:9-29)."""
    name = config.objective
    if name not in _OBJECTIVES:
        log.fatal("Unknown objective type name: %s", name)
    cls = _OBJECTIVES[name]
    try:
        return cls(config)
    except TypeError:
        return cls()
