"""Test-support utilities shipped with the package (not test code):
``lightgbm_tpu.testing.faults`` is the fault-injection harness used by
``tests/test_fault_tolerance.py`` to prove each recovery path
(docs/FAULT_TOLERANCE.md) end-to-end."""

from . import faults  # noqa: F401

__all__ = ["faults"]
