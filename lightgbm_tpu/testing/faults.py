"""Fault-injection harness: make the failures the fault-tolerance layer
claims to survive actually happen, deterministically, in-process.

Each injector is a context manager that patches exactly one seam and
restores it on exit, so tests (tests/test_fault_tolerance.py, marker
``faults``) can prove recovery paths end-to-end instead of unit-testing
fragments:

- ``poison_gradients``: non-finite gradients at one boosting iteration
  (exercises ``nan_policy`` containment, docs/FAULT_TOLERANCE.md);
- ``fail_distributed_init``: the next N ``jax.distributed.initialize``
  attempts raise (exercises the multihost retry/backoff loop);
- ``torn_snapshot_write``: a snapshot write crashes mid-file (exercises
  the atomic tmp+``os.replace`` protocol and checksum fallback);
- ``truncate_file`` / ``flip_byte``: corrupt a file on disk after the
  fact (bit rot / torn storage on an already-written snapshot);
- rank-level injectors (the distributed chaos suite in
  tests/test_dist_chaos.py, marker ``dist_chaos``): callback factories
  for ``engine.train``'s callback seam, each gated to ONE rank of a
  multi-process run — ``kill_rank`` (SIGKILL at a boosting iteration:
  the preempted worker), ``hang_rank`` (the iteration blocks: a wedged
  host), ``delay_rank`` (added per-iteration latency: the straggler),
  ``corrupt_rank_state`` (silently perturb one rank's replicated score
  cache or trees: the desync the consistency check exists for);
- serving injectors (PR 9, the chaos suite in tests/test_serve_chaos.py,
  marker ``chaos``): ``wedge_replica`` (a replica's device predict
  blocks until release — the classic hung-device failure),
  ``poison_predict`` (predict raises on one replica),
  ``slow_replica`` (added service latency — the straggler),
  ``skew_predictions`` (every replica of one model returns values
  shifted by a constant — the silently-wrong model only the lifecycle
  quality guardrail can catch),
  ``fail_warmup`` (``CompiledForest.warmup`` raises — a hot reload
  dying mid-warm).  Each patches the replica's FOREST as well as its
  live batcher, so the health watchdog's synthetic probes see the same
  fault the traffic does (and recovery probes succeed only once the
  fault is lifted).

- data-corpus injectors (PR 13, the ingest chaos suite in
  tests/test_ingest_chaos.py + tests/test_fuzz_ingest.py):
  ``mangle_rows`` (unparseable tokens in a numeric column),
  ``ragged_rows`` (field-count drift), ``truncate_mid_row`` (torn
  write), ``concurrent_append`` (a producer still writing the file
  between the two-round loader's rounds), ``corrupt_model_file``
  (truncated / footer-less / bit-rotted model artifacts) — each
  deterministic and returning the ground-truth line numbers the
  quarantine accounting is checked against.

- resource-exhaustion injectors (PR 15, the resource chaos suite in
  tests/test_resource_chaos.py, marker ``resource_chaos``):
  ``fail_writes(errno, path_glob)`` (every guarded write raises — the
  already-full/read-only/quota'd/fd-starved disk, through the ONE
  diskguard hook every non-artifact sink funnels through),
  ``disk_full_after(n_bytes)`` (the volume filling up mid-run),
  ``oom_on_program(name)`` (RESOURCE_EXHAUSTED at the InstrumentedJit
  dispatch seam — the late XLA allocation death the admission gate
  cannot always predict).

None of these are test-only hacks around private invariants: they throw
real exceptions through real call stacks, which is the point.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator, Optional


class InjectedCrash(RuntimeError):
    """Raised by injectors simulating a hard process death.  Distinct
    type so tests can assert THIS crash surfaced, not some other bug."""


@contextlib.contextmanager
def poison_gradients(booster, at_iteration: int,
                     value: float = float("nan"),
                     times: int = 1) -> Iterator[object]:
    """Make the objective emit ``value`` for every gradient, ``times``
    times, starting at boosting iteration ``at_iteration`` (0-based,
    absolute ``iter_`` index).  A transient fault by default (one
    poisoned round: under ``nan_policy=skip_tree`` the retry of the
    same iteration index then succeeds); pass a large ``times`` for a
    persistently degenerate objective.

    Accepts a ``basic.Booster`` or a raw ``models.gbdt.GBDT``.  The
    injector wraps the instance's ``_gradients`` hook and forces the
    per-stage path (``LGBT_NO_FUSED_STEP``) while active — the fused
    step bakes the objective into one compiled program, so a host-side
    wrapper could never fire inside it."""
    gb = getattr(booster, "_booster", booster)
    orig = gb._gradients
    fired = [0]

    def poisoned_gradients():
        grad, hess = orig()
        if gb.iter_ >= at_iteration and fired[0] < times:
            fired[0] += 1
            import jax.numpy as jnp
            grad = jnp.full_like(grad, value)
        return grad, hess

    old_env = os.environ.get("LGBT_NO_FUSED_STEP")
    os.environ["LGBT_NO_FUSED_STEP"] = "1"
    gb._gradients = poisoned_gradients
    try:
        yield gb
    finally:
        gb.__dict__.pop("_gradients", None)
        if old_env is None:
            os.environ.pop("LGBT_NO_FUSED_STEP", None)
        else:
            os.environ["LGBT_NO_FUSED_STEP"] = old_env


@contextlib.contextmanager
def fail_distributed_init(times: int = 1,
                          message: str = "injected coordinator connect "
                          "failure") -> Iterator[dict]:
    """Patch ``jax.distributed.initialize`` to raise ``RuntimeError``
    for the first ``times`` calls; later calls succeed as recorded
    no-ops (the harness cannot bring up a real coordinator inside one
    test process).  Yields a stats dict: ``failed`` / ``succeeded``
    call counts and the ``kwargs`` of every attempt."""
    import jax

    stats = {"failed": 0, "succeeded": 0, "kwargs": []}
    orig = jax.distributed.initialize

    def flaky_initialize(*args, **kwargs):
        stats["kwargs"].append(kwargs)
        if stats["failed"] < times:
            stats["failed"] += 1
            raise RuntimeError(message)
        stats["succeeded"] += 1

    jax.distributed.initialize = flaky_initialize
    try:
        yield stats
    finally:
        jax.distributed.initialize = orig


@contextlib.contextmanager
def torn_snapshot_write(after_bytes: int = 64) -> Iterator[dict]:
    """Crash every ``lightgbm_tpu.snapshot.write_snapshot`` after
    ``after_bytes`` bytes have reached the temp file — the moment a real
    preemption would strike mid-checkpoint.  The atomicity contract
    under test: no final snapshot file is ever produced or damaged, so
    resume falls back to the previous good one.  Yields a stats dict
    with the ``torn`` paths."""
    from .. import snapshot as snapmod

    stats = {"torn": []}
    orig = snapmod.write_snapshot

    def torn_write(path, state):
        blob = snapmod._encode(state)
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(path + ".tmp", "wb") as fh:
            fh.write(blob[:max(int(after_bytes), 0)])
        stats["torn"].append(path)
        raise InjectedCrash(
            f"snapshot write to {path} torn after {after_bytes} bytes")

    snapmod.write_snapshot = torn_write
    try:
        yield stats
    finally:
        snapmod.write_snapshot = orig


def truncate_file(path: str, keep_bytes: Optional[int] = None) -> None:
    """Truncate ``path`` in place (default: half its size) — an
    already-committed snapshot damaged by torn storage.  The checksummed
    reader must treat the result as absent."""
    size = os.path.getsize(path)
    keep = size // 2 if keep_bytes is None else max(int(keep_bytes), 0)
    with open(path, "r+b") as fh:
        fh.truncate(keep)


# ---------------------------------------------------------------------------
# rank-level fault injectors (parallel/ fault tolerance,
# docs/FAULT_TOLERANCE.md §Distributed)
#
# These are CALLBACK factories, not context managers: the failure seam
# is a boosting iteration of a specific rank inside engine.train's
# callback-driven loop (the distributed chaos workers pass them via
# ``callbacks=[...]``), and the kill/hang variants never return to
# restore anything anyway.


def _this_rank() -> int:
    try:
        from ..parallel.multihost import process_rank_world
        return process_rank_world()[0]
    except Exception:
        return 0


def _rank_matches(rank: Optional[int]) -> bool:
    return rank is None or int(rank) == _this_rank()


def kill_rank(at_iteration: int, rank: Optional[int] = None):
    """Before-iteration callback: SIGKILL this process when boosting
    iteration ``at_iteration`` begins on ``rank`` (None = any rank) —
    the preempted-worker failure, a hard death no ``finally`` softens.
    The surviving ranks block in that round's collective until the
    watchdog aborts them (parallel/watchdog.py)."""
    import signal

    def cb(env):
        if env.iteration >= int(at_iteration) and _rank_matches(rank):
            os.kill(os.getpid(), signal.SIGKILL)
    cb.before_iteration = True
    cb.order = -99
    return cb


def hang_rank(at_iteration: int, rank: Optional[int] = None,
              hang_s: float = 3600.0):
    """Before-iteration callback: boosting iteration ``at_iteration`` on
    ``rank`` blocks for ``hang_s`` (or until the callback's ``release``
    event is set) — the alive-but-wedged host whose heartbeats keep
    flowing while its collectives never arrive; the peers' round
    deadline is what must trip."""
    release = threading.Event()

    def cb(env):
        if env.iteration == int(at_iteration) and _rank_matches(rank):
            release.wait(float(hang_s))
    cb.before_iteration = True
    cb.order = -99
    cb.release = release
    return cb


def delay_rank(at_iteration: int, delay_s: float, times: int = 1,
               rank: Optional[int] = None):
    """Before-iteration callback: add ``delay_s`` of latency to
    ``times`` iterations starting at ``at_iteration`` on ``rank`` — the
    straggler.  Results stay correct; only time is poisoned (the
    per-rank ``comm_seconds`` histograms are what makes it visible)."""
    fired = [0]

    def cb(env):
        if env.iteration >= int(at_iteration) and fired[0] < int(times) \
                and _rank_matches(rank):
            fired[0] += 1
            time.sleep(float(delay_s))
    cb.before_iteration = True
    cb.order = -99
    cb.fired = fired
    return cb


def corrupt_rank_state(at_iteration: int, rank: Optional[int] = None,
                       field: str = "score", scale: float = 2.0):
    """After-iteration callback: silently corrupt ONE rank's replicated
    training state after iteration ``at_iteration`` completes — the
    desync failure ``distributed_consistency_check`` exists to catch
    (a flipped HBM bit, a diverged rematerialization).  ``field``:

    - ``"score"``: add ``scale`` to one element of the train score cache
      (poisons every later gradient on that rank);
    - ``"tree"``: scale the newest tree's leaf values (poisons the
      model itself).
    """
    if field not in ("score", "tree"):
        raise ValueError(f"corrupt_rank_state: unknown field {field!r}")
    fired = [False]

    def cb(env):
        if fired[0] or env.iteration < int(at_iteration) \
                or not _rank_matches(rank):
            return
        fired[0] = True
        gb = getattr(env.model, "_booster", env.model)
        if field == "score":
            gb.train_data.score = gb.train_data.score.at[0, 0].add(
                float(scale))
        else:
            gb._flush_pending()
            if gb._models:
                tree = gb._models[-1]
                tree.leaf_value = tree.leaf_value * float(scale)
    cb.order = 99
    cb.fired = fired
    return cb


# ---------------------------------------------------------------------------
# serving fault injectors (serve/fleet.py + serve/health.py)


def _find_replica(fleet, replica_id: int, model: str = "primary"):
    with fleet._cond:
        rs = fleet._primary if model == "primary" else fleet._canary
        if rs is None:
            raise ValueError(f"fleet has no {model!r} replica set")
        for rep in rs.replicas:
            if rep.replica_id == int(replica_id):
                return rep
    raise ValueError(f"no replica {replica_id} in {model!r}")


@contextlib.contextmanager
def _patched_predict(fleet, replica_id: int, wrap,
                     model: str = "primary") -> Iterator[dict]:
    """Shared plumbing: wrap ``replica.forest.batched_fn`` with ``wrap``
    (fault view for future batchers AND the watchdog's probes) and swap
    the live batcher's ``predict_fn`` to the same faulty callable (fault
    view for traffic already flowing).  Restores both on exit — but a
    batcher REPLACED meanwhile (ejection -> re-admission builds a fresh
    one from the forest) is left alone: it was built from the restored
    forest or will be on the next probe."""
    rep = _find_replica(fleet, replica_id, model)
    stats = {"replica": rep, "calls": 0}
    orig_batched_fn = rep.forest.batched_fn

    def faulty_batched_fn():
        inner = orig_batched_fn()

        def fn(rows):
            stats["calls"] += 1
            return wrap(inner, rows)
        return fn

    rep.forest.batched_fn = faulty_batched_fn
    patched_batcher = rep.batcher
    orig_predict_fn = patched_batcher.predict_fn
    patched_batcher.predict_fn = faulty_batched_fn()
    try:
        yield stats
    finally:
        del rep.forest.batched_fn          # instance attr -> class method
        if rep.batcher is patched_batcher:
            patched_batcher.predict_fn = orig_predict_fn


@contextlib.contextmanager
def wedge_replica(fleet, replica_id: int,
                  model: str = "primary") -> Iterator[dict]:
    """Wedge one replica: its device predict (traffic AND probes)
    blocks until the context exits — the hung-device failure the health
    watchdog's stall detector exists for.  On exit the wedge releases,
    so the next probe succeeds and the replica can be re-admitted.
    Yields a stats dict whose ``release`` event can lift the wedge
    early."""
    release = threading.Event()

    def wedged(inner, rows):
        release.wait()
        return inner(rows)

    with _patched_predict(fleet, replica_id, wedged, model) as stats:
        stats["release"] = release
        try:
            yield stats
        finally:
            release.set()


@contextlib.contextmanager
def poison_predict(fleet, replica_id: int, model: str = "primary",
                   error: Optional[BaseException] = None) -> Iterator[dict]:
    """Every predict on one replica raises (a poisoned compile, a
    device in a bad state).  Probes fail too, so the replica stays
    ejected until the context exits."""
    exc = error or InjectedCrash(
        f"injected predict poison on replica {replica_id}")

    def poisoned(inner, rows):
        raise exc

    with _patched_predict(fleet, replica_id, poisoned, model) as stats:
        stats["error"] = exc
        yield stats


@contextlib.contextmanager
def slow_replica(fleet, replica_id: int, delay_s: float,
                 model: str = "primary") -> Iterator[dict]:
    """One replica serves ``delay_s`` slower than it should — the
    straggler the EWMA latency-outlier rule is for.  Results stay
    correct; only time is poisoned."""
    def slowed(inner, rows):
        time.sleep(float(delay_s))
        return inner(rows)

    with _patched_predict(fleet, replica_id, slowed, model) as stats:
        stats["delay_s"] = float(delay_s)
        yield stats


@contextlib.contextmanager
def skew_predictions(fleet, offset: float,
                     model: str = "canary") -> Iterator[dict]:
    """Every prediction from EVERY replica of ``model`` comes back
    shifted by ``offset`` — the silently-wrong model (a mis-exported
    artifact, a feature-pipeline skew) that serves fast, errors never,
    and is purely WORSE.  Latency and error guardrails cannot see it;
    the labeled-feedback quality gate (rolling logloss/AUC,
    serve/lifecycle.py) is the one that must trip.  Results stay
    shaped/typed correctly; only the values are poisoned."""
    import numpy as np

    off = float(offset)

    def skewed(inner, rows):
        raw, out = inner(rows)
        return np.asarray(raw) + off, np.asarray(out) + off

    with fleet._cond:
        rs = fleet._primary if model == "primary" else fleet._canary
        if rs is None:
            raise ValueError(f"fleet has no {model!r} replica set")
        ids = [rep.replica_id for rep in rs.replicas]
    with contextlib.ExitStack() as stack:
        stats = {"offset": off, "replicas": ids, "per_replica": [
            stack.enter_context(_patched_predict(fleet, rid, skewed, model))
            for rid in ids]}
        yield stats


@contextlib.contextmanager
def skew_features(fleet, features, shift: float,
                  model: str = "canary") -> Iterator[dict]:
    """Every row served by EVERY replica of ``model`` arrives with the
    given feature columns shifted by ``shift`` — upstream feature-
    pipeline drift (a stale join, a units change) that predictions
    alone cannot localise.  The drift observatory is the gate that must
    see it: within a window, ``drift_psi`` for exactly these features
    crosses threshold and the lifecycle drift gate names them.  The
    skewed rows flow through the real device path, so the drift
    collector observes them as served traffic."""
    import numpy as np

    feats = [int(f) for f in features]
    off = float(shift)

    def skewed(inner, rows):
        rows = np.array(rows, copy=True)
        rows[:, feats] += off
        return inner(rows)

    with fleet._cond:
        rs = fleet._primary if model == "primary" else fleet._canary
        if rs is None:
            raise ValueError(f"fleet has no {model!r} replica set")
        ids = [rep.replica_id for rep in rs.replicas]
    with contextlib.ExitStack() as stack:
        stats = {"features": feats, "shift": off, "replicas": ids,
                 "per_replica": [
                     stack.enter_context(
                         _patched_predict(fleet, rid, skewed, model))
                     for rid in ids]}
        yield stats


@contextlib.contextmanager
def fail_warmup(times: int = 1) -> Iterator[dict]:
    """The next ``times`` ``CompiledForest.warmup`` calls raise — a hot
    reload crashing mid-warm on a replica device.  The reload contract
    under test: the serving generation, its predictions, and the
    compile ledger stay untouched (ModelManager.reload rolls back)."""
    from ..serve.forest import CompiledForest

    stats = {"failed": 0}
    orig = CompiledForest.warmup

    def failing_warmup(self, *args, **kwargs):
        if stats["failed"] < int(times):
            stats["failed"] += 1
            raise InjectedCrash(
                f"injected warmup failure ({stats['failed']}/{times})")
        return orig(self, *args, **kwargs)

    CompiledForest.warmup = failing_warmup
    try:
        yield stats
    finally:
        CompiledForest.warmup = orig


# ---------------------------------------------------------------------------
# data-corpus injectors (io/ data-boundary hardening,
# docs/FAULT_TOLERANCE.md §Data boundary).  Deterministic (seeded) file
# mutators producing exactly the dirt the IngestGuard classifies —
# tests/test_ingest_chaos.py trains through them and pins quarantine
# accounting, and tests/test_fuzz_ingest.py sprays random variants.


def _detect_delim(line: str) -> str:
    if "\t" in line:
        return "\t"
    if "," in line:
        return ","
    return " "


def mangle_rows(path: str, fraction: float = 0.05, seed: int = 0,
                token: str = "##garbage##", skip_header: bool = False
                ) -> list:
    """Replace one feature field of ~``fraction`` of the data rows with
    an unparseable token (the classic exporter bug: a stray string in a
    numeric column).  Returns the SORTED 1-based file line numbers
    mangled — the ground truth the quarantine sink is checked against."""
    import numpy as np

    with open(path, "r") as fh:
        lines = fh.read().splitlines()
    first = 1 if skip_header else 0
    data_idx = [i for i in range(first, len(lines)) if lines[i].strip()]
    k = max(1, int(round(fraction * len(data_idx))))
    rng = np.random.RandomState(seed)
    chosen = sorted(rng.choice(len(data_idx), size=min(k, len(data_idx)),
                               replace=False))
    mangled = []
    for c in chosen:
        i = data_idx[int(c)]
        delim = _detect_delim(lines[i])
        parts = lines[i].split(delim)
        parts[min(1, len(parts) - 1)] = token  # a feature, not the label
        lines[i] = delim.join(parts)
        mangled.append(i + 1)
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return mangled


def ragged_rows(path: str, fraction: float = 0.05, seed: int = 0,
                mode: str = "drop", skip_header: bool = False) -> list:
    """Drop (``mode="drop"``) or duplicate (``mode="add"``) the last
    field of ~``fraction`` of the data rows — the torn-write /
    schema-drift shape of dirt.  Returns sorted 1-based line numbers."""
    import numpy as np

    if mode not in ("drop", "add"):
        raise ValueError(f"ragged_rows: unknown mode {mode!r}")
    with open(path, "r") as fh:
        lines = fh.read().splitlines()
    first = 1 if skip_header else 0
    data_idx = [i for i in range(first, len(lines)) if lines[i].strip()]
    k = max(1, int(round(fraction * len(data_idx))))
    rng = np.random.RandomState(seed)
    chosen = sorted(rng.choice(len(data_idx), size=min(k, len(data_idx)),
                               replace=False))
    out = []
    for c in chosen:
        i = data_idx[int(c)]
        delim = _detect_delim(lines[i])
        parts = lines[i].split(delim)
        if mode == "drop" and len(parts) > 1:
            parts = parts[:-1]
        else:
            parts = parts + [parts[-1]]
        lines[i] = delim.join(parts)
        out.append(i + 1)
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return out


def truncate_mid_row(path: str) -> int:
    """Chop the file in the MIDDLE of its last data row (a torn
    write/partial download): the resulting final line is ragged or
    holds a half-number.  Returns the 1-based line number truncated."""
    with open(path, "rb") as fh:
        blob = fh.read()
    body = blob.rstrip(b"\n")
    last_nl = body.rfind(b"\n")
    last_line = body[last_nl + 1:]
    keep = last_nl + 1 + max(1, len(last_line) // 2)
    with open(path, "wb") as fh:
        fh.write(blob[:keep])
    return body[:last_nl + 1].count(b"\n") + 1


@contextlib.contextmanager
def concurrent_append(path: str, extra_text: str,
                      after_reads: int = 2) -> Iterator[dict]:
    """Append ``extra_text`` to ``path`` after its ``after_reads``-th
    full read pass — for the two-round loader (round 1a count, round 1b
    sample, round 2 fill) the default lands the append exactly at the
    round-1/round-2 boundary, simulating a concurrent producer still
    writing the file.  The loader must refuse with a named drift
    ``LightGBMError``, never mis-bin.  Yields a stats dict
    (``completed`` read passes, ``appended`` flag)."""
    from ..io import streaming

    orig = streaming._numbered_data_lines
    state = {"completed": 0, "appended": False}

    def racing_lines(p, skip_header):
        if os.path.abspath(str(p)) == os.path.abspath(path):
            if state["completed"] >= int(after_reads) \
                    and not state["appended"]:
                state["appended"] = True
                with open(path, "a") as fh:
                    fh.write(extra_text)
            yield from orig(p, skip_header)
            state["completed"] += 1
        else:
            yield from orig(p, skip_header)

    streaming._numbered_data_lines = racing_lines
    try:
        yield state
    finally:
        streaming._numbered_data_lines = orig


def corrupt_model_file(path: str, mode: str = "truncate_tree") -> str:
    """Damage a saved model file the way real storage does:

    - ``"truncate_tree"``: chop the text mid-way through the last tree
      block (half-written upload);
    - ``"chop_footer"``: cut everything from the last ``Tree=`` on —
      whole trees missing AND no ``feature importances`` footer;
    - ``"garbage_field"``: replace the first ``leaf_value`` number with
      a non-numeric token (bit rot under a valid length).

    Returns a short description of what was done.  The loader contract
    under test: ``LightGBMError`` naming the damage — serve ``/reload``
    turns it into a clean 400, never a half-loaded forest."""
    with open(path, "r") as fh:
        text = fh.read()
    if mode == "truncate_tree":
        last = text.rfind("Tree=")
        if last < 0:
            raise ValueError(f"{path} has no Tree= blocks")
        cut = last + (len(text) - last) // 2
        out = text[:cut]
        what = f"truncated mid-tree at byte {cut}"
    elif mode == "chop_footer":
        last = text.rfind("Tree=")
        if last < 0:
            raise ValueError(f"{path} has no Tree= blocks")
        out = text[:last]
        what = f"chopped from last Tree= (byte {last})"
    elif mode == "garbage_field":
        if "leaf_value=" not in text:
            raise ValueError(f"{path} has no leaf_value section")
        out = text.replace("leaf_value=", "leaf_value=@@rot@@ ", 1)
        what = "first leaf_value poisoned with a non-numeric token"
    else:
        raise ValueError(f"corrupt_model_file: unknown mode {mode!r}")
    with open(path, "w") as fh:
        fh.write(out)
    return what


# ---------------------------------------------------------------------------
# resource-exhaustion injectors (utils/diskguard.py + utils/resource.py,
# docs/FAULT_TOLERANCE.md §Resource exhaustion).  The disk injectors
# install the ONE module-level hook every guarded write passes through,
# so the injected OSError travels the real call stack of the real sink
# (events JSONL, compile ledger, quarantine, snapshot tmp, serve state);
# the OOM injector raises at the InstrumentedJit dispatch seam — exactly
# where a real XLA RESOURCE_EXHAUSTED surfaces.


@contextlib.contextmanager
def fail_writes(errno_code: int, path_glob: str = "*",
                armed: bool = True) -> Iterator[dict]:
    """Every guarded write to a path matching ``path_glob`` raises a
    real ``OSError(errno_code)`` while ``stats["armed"]`` is True — the
    full-disk (ENOSPC), quota (EDQUOT), read-only-remount (EROFS) and
    fd-exhaustion (EMFILE) failures the diskguard layer classifies.
    Start with ``armed=False`` and flip ``stats["armed"]`` from a
    training callback to strike mid-run.  Yields stats: ``fired`` /
    ``paths`` (every injected failure) and the live ``armed`` flag."""
    import fnmatch

    from ..utils import diskguard

    if diskguard._fault_hook is not None:
        raise RuntimeError("a diskguard fault hook is already installed")
    stats = {"fired": 0, "paths": [], "armed": bool(armed)}

    def hook(path: str, nbytes: int) -> None:
        if stats["armed"] and fnmatch.fnmatch(path, path_glob):
            stats["fired"] += 1
            stats["paths"].append(path)
            raise OSError(int(errno_code), os.strerror(int(errno_code)),
                          path)

    diskguard._fault_hook = hook
    try:
        yield stats
    finally:
        diskguard._fault_hook = None


@contextlib.contextmanager
def disk_full_after(n_bytes: int, path_glob: str = "*") -> Iterator[dict]:
    """The disk accepts ``n_bytes`` more guarded-write traffic (matching
    ``path_glob``), then every further write raises ENOSPC — the
    volume-fills-up-mid-run failure, as opposed to ``fail_writes``'s
    already-full disk.  Yields stats: ``written`` (bytes accepted),
    ``fired`` (writes refused)."""
    import errno as _errno
    import fnmatch

    from ..utils import diskguard

    if diskguard._fault_hook is not None:
        raise RuntimeError("a diskguard fault hook is already installed")
    stats = {"written": 0, "fired": 0, "budget": int(n_bytes)}

    def hook(path: str, nbytes: int) -> None:
        if not fnmatch.fnmatch(path, path_glob):
            return
        if stats["written"] + int(nbytes) > stats["budget"]:
            stats["fired"] += 1
            raise OSError(_errno.ENOSPC, os.strerror(_errno.ENOSPC), path)
        stats["written"] += int(nbytes)

    diskguard._fault_hook = hook
    try:
        yield stats
    finally:
        diskguard._fault_hook = None


def make_resource_exhausted(program: str,
                            nbytes: int = 123456789) -> BaseException:
    """A device-OOM exception shaped like the real thing: the genuine
    ``XlaRuntimeError`` class when this jax build exposes it (so
    ``except``-clause behavior matches production), else a RuntimeError
    carrying the same RESOURCE_EXHAUSTED text the classifier keys on."""
    msg = (f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
           f"{int(nbytes)} bytes. (injected for program {program!r})")
    try:
        from jax._src.lib import xla_client
        return xla_client.XlaRuntimeError(msg)
    except Exception:
        return RuntimeError(msg)


@contextlib.contextmanager
def oom_on_program(program: str, times: int = 1) -> Iterator[dict]:
    """The next ``times`` dispatches of the jitted program named
    ``program`` die with RESOURCE_EXHAUSTED at the ``InstrumentedJit``
    dispatch seam — the late XLA allocation failure the admission gate
    cannot always predict (fragmentation, a concurrent tenant).  The
    containment contract under test: the error surfaces as a named
    ``DeviceOOM`` diagnosis (program, shapes, memwatch snapshot,
    admission table), never a raw backtrace.  Yields stats with the
    ``fired`` count."""
    from ..obs.compile_ledger import InstrumentedJit

    stats = {"fired": 0}
    orig = InstrumentedJit._dispatch

    def oom_dispatch(self, *args, **kwargs):
        if self.program == str(program) and stats["fired"] < int(times):
            stats["fired"] += 1
            raise make_resource_exhausted(self.program)
        return orig(self, *args, **kwargs)

    InstrumentedJit._dispatch = oom_dispatch
    try:
        yield stats
    finally:
        InstrumentedJit._dispatch = orig


def flip_byte(path: str, offset: int = -1) -> None:
    """XOR one byte of ``path`` (default: the last byte — payload, past
    every header field) to simulate silent bit rot under a still-valid
    length."""
    with open(path, "r+b") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        pos = offset if offset >= 0 else size + offset
        fh.seek(pos)
        b = fh.read(1)
        fh.seek(pos)
        fh.write(bytes([b[0] ^ 0xFF]))
