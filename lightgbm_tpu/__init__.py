"""LightGBM-TPU: a TPU-native gradient boosting framework.

A from-scratch re-design of the LightGBM v2 feature set for TPU hardware:
histogram construction and leaf-wise split search run as fused XLA/Pallas
programs over a `jax.sharding.Mesh`; the reference's socket/MPI collective
layer (src/network/) is replaced by XLA collectives (psum/psum_scatter/
all_gather) inside shard_map.
"""

__version__ = "0.1.0"

from .config import Config  # noqa: F401
from .io import BinnedDataset, BinMapper, Metadata  # noqa: F401
from .basic import Booster, Dataset  # noqa: F401
from .callback import (early_stopping, log_telemetry,  # noqa: F401
                       print_evaluation, record_evaluation, reset_parameter)
from . import obs  # noqa: F401
from . import serve  # noqa: F401
from .engine import CVBooster, cv, train, train_delta  # noqa: F401
from .sklearn import (LGBMClassifier, LGBMModel,  # noqa: F401
                      LGBMRanker, LGBMRegressor)
from .utils.log import LightGBMError  # noqa: F401

try:
    from .plotting import plot_importance, plot_metric, plot_tree  # noqa: F401
    _PLOTTING = ["plot_importance", "plot_metric", "plot_tree"]
except ImportError:  # matplotlib not installed
    _PLOTTING = []

__all__ = ["Dataset", "Booster", "Config",
           "train", "train_delta", "cv", "CVBooster",
           "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
           "print_evaluation", "record_evaluation", "reset_parameter",
           "early_stopping", "log_telemetry", "obs", "serve",
           "LightGBMError"] + _PLOTTING
