"""Scikit-learn API wrappers (reference python-package/lightgbm/sklearn.py).

LGBMModel + LGBMRegressor / LGBMClassifier (label encoding, predict_proba)
/ LGBMRanker (query groups), with the same custom-objective translation:
an sklearn-style ``objective(y_true, y_pred)`` callable is wrapped into the
engine's ``fobj(preds, dataset) -> (grad, hess)`` signature
(sklearn.py:15-122).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train as _train
from .utils.log import LightGBMError

try:
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    from sklearn.preprocessing import LabelEncoder as _LabelEncoder
    SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover - sklearn is baked into the image
    SKLEARN_INSTALLED = False
    _SKBase = object

    class _SKClassifier:  # type: ignore
        pass

    class _SKRegressor:  # type: ignore
        pass

    class _LabelEncoder:  # type: ignore
        def fit(self, y):
            self.classes_ = np.unique(np.asarray(y))
            return self

        def transform(self, y):
            return np.searchsorted(self.classes_, np.asarray(y))

        def fit_transform(self, y):
            return self.fit(y).transform(y)


class _ObjectiveFunctionWrapper:
    """Translate sklearn fobj(y_true, y_pred[, group]) -> (grad, hess)
    into engine fobj(preds, dataset) (sklearn.py:15-84)."""

    def __init__(self, func):
        self.func = func

    def __call__(self, preds, dataset):
        labels = np.asarray(dataset.get_label())
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_group())
        else:
            raise TypeError(
                f"Self-defined objective should have 2 or 3 arguments, "
                f"got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Translate sklearn feval(y_true, y_pred[, weight][, group]) ->
    (name, value, is_higher_better) (sklearn.py:85-122)."""

    def __init__(self, func):
        self.func = func

    def __call__(self, preds, dataset):
        labels = np.asarray(dataset.get_label())
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(
            f"Self-defined eval function should have 2, 3 or 4 arguments, "
            f"got {argc}")


class LGBMModel(_SKBase):
    """Implementation of the scikit-learn API for LightGBM-TPU
    (sklearn.py:123)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 subsample_for_bin=50000, objective="regression",
                 min_split_gain=0, min_child_weight=5, min_child_samples=10,
                 subsample=1, subsample_freq=1, colsample_bytree=1,
                 reg_alpha=0, reg_lambda=0, scale_pos_weight=1,
                 is_unbalance=False, seed=0, nthread=-1, silent=True,
                 sigmoid=1.0, huber_delta=1.0, gaussian_eta=1.0, fair_c=1.0,
                 poisson_max_delta_step=0.7,
                 max_position=20, label_gain=None,
                 drop_rate=0.1, skip_drop=0.5, max_drop=50,
                 uniform_drop=False, xgboost_dart_mode=False):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.is_unbalance = is_unbalance
        self.seed = seed
        self.nthread = nthread
        self.silent = silent
        self.sigmoid = sigmoid
        self.huber_delta = huber_delta
        self.gaussian_eta = gaussian_eta
        self.fair_c = fair_c
        self.poisson_max_delta_step = poisson_max_delta_step
        self.max_position = max_position
        self.label_gain = label_gain
        self.drop_rate = drop_rate
        self.skip_drop = skip_drop
        self.max_drop = max_drop
        self.uniform_drop = uniform_drop
        self.xgboost_dart_mode = xgboost_dart_mode
        self._Booster: Optional[Booster] = None
        self._evals_result: Optional[dict] = None
        self._best_iteration = -1
        self._other_params: Dict[str, Any] = {}
        self._objective = objective
        self.class_weight = None

    # sklearn plumbing ---------------------------------------------------
    def get_params(self, deep=True):
        params = super().get_params(deep=deep) if SKLEARN_INSTALLED else {
            k: getattr(self, k) for k in self._param_names()}
        params.update(self._other_params)
        return params

    @classmethod
    def _param_names(cls):
        import inspect
        return [p for p in inspect.signature(cls.__init__).parameters
                if p != "self"]

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if not hasattr(type(self), key):
                self._other_params[key] = value
        return self

    def _params_for_engine(self) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "objective": self.objective
            if not callable(self.objective) else "none",
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "max_bin": self.max_bin,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "scale_pos_weight": self.scale_pos_weight,
            "is_unbalance": self.is_unbalance,
            "data_random_seed": self.seed,
            "verbosity": 0 if self.silent else 1,
            "sigmoid": self.sigmoid,
            "huber_delta": self.huber_delta,
            "gaussian_eta": self.gaussian_eta,
            "fair_c": self.fair_c,
            "poisson_max_delta_step": self.poisson_max_delta_step,
            "max_position": self.max_position,
            "drop_rate": self.drop_rate,
            "skip_drop": self.skip_drop,
            "max_drop": self.max_drop,
            "uniform_drop": self.uniform_drop,
            "xgboost_dart_mode": self.xgboost_dart_mode,
        }
        if self.label_gain is not None:
            params["label_gain"] = list(self.label_gain)
        params.update(self._other_params)
        return params

    # fitting ------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_sample_weight=None, eval_init_score=None,
            eval_group=None, eval_metric=None, early_stopping_rounds=None,
            verbose=True, feature_name="auto", categorical_feature="auto",
            callbacks=None):
        params = self._params_for_engine()
        fobj = (_ObjectiveFunctionWrapper(self.objective)
                if callable(self.objective) else None)
        feval = (_EvalFunctionWrapper(eval_metric)
                 if callable(eval_metric) else None)
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric

        train_set = Dataset(X, label=y, weight=sample_weight,
                            group=group, params=params)
        if init_score is not None:
            train_set.set_init_score(init_score)

        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                vs = train_set.create_valid(vx, vy, weight=vw, group=vg)
                if vi is not None:
                    vs.set_init_score(vi)
                valid_sets.append(vs)

        evals_result: Dict[str, Any] = {}
        self._Booster = _train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result, verbose_eval=verbose,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks)
        self._evals_result = evals_result or None
        self._best_iteration = self._Booster.best_iteration
        return self

    # prediction ---------------------------------------------------------
    def predict(self, X, raw_score=False, num_iteration=-1):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit first")
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration)

    def apply(self, X, num_iteration=-1):
        """Leaf indices of each sample per tree."""
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit first")
        return self._Booster.predict(X, pred_leaf=True,
                                     num_iteration=num_iteration)

    # accessors ----------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster

    @property
    def best_iteration(self) -> int:
        return self._best_iteration

    @property
    def best_iteration_(self) -> int:
        """sklearn-convention alias (reference sklearn.py exposes the
        trailing-underscore spelling)."""
        return self._best_iteration

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("No booster found, call fit first")
        return self._Booster.feature_importance()

    @property
    def telemetry_(self) -> Dict[str, Any]:
        """Run telemetry snapshot (counters/gauges/comm account) from the
        fitted booster — see Booster.telemetry / docs/OBSERVABILITY.md."""
        return self.booster_.telemetry()


class LGBMRegressor(LGBMModel, _SKRegressor):

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 subsample_for_bin=50000, objective="regression", **kwargs):
        super().__init__(boosting_type=boosting_type, num_leaves=num_leaves,
                         max_depth=max_depth, learning_rate=learning_rate,
                         n_estimators=n_estimators, max_bin=max_bin,
                         subsample_for_bin=subsample_for_bin,
                         objective=objective, **kwargs)


class LGBMClassifier(LGBMModel, _SKClassifier):

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 subsample_for_bin=50000, objective="binary", **kwargs):
        super().__init__(boosting_type=boosting_type, num_leaves=num_leaves,
                         max_depth=max_depth, learning_rate=learning_rate,
                         n_estimators=n_estimators, max_bin=max_bin,
                         subsample_for_bin=subsample_for_bin,
                         objective=objective, **kwargs)

    def fit(self, X, y, **kwargs):
        self._le = _LabelEncoder().fit(y)
        y_enc = self._le.transform(y)
        self.classes_ = self._le.classes_
        self.n_classes_ = len(self.classes_)
        if self.n_classes_ > 2:
            if not callable(self.objective):
                self.objective = "multiclass"
            self._other_params["num_class"] = self.n_classes_
        eval_set = kwargs.get("eval_set")
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            kwargs["eval_set"] = [(vx, self._le.transform(vy))
                                  for vx, vy in eval_set]
        return super().fit(X, y_enc, **kwargs)

    def predict(self, X, raw_score=False, num_iteration=-1):
        probs = self.predict_proba(X, raw_score=raw_score,
                                   num_iteration=num_iteration)
        if raw_score:
            return probs
        if probs.ndim > 1:
            idx = np.argmax(probs, axis=1)
        else:
            idx = (probs > 0.5).astype(np.int64)
        return self._le.classes_[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=-1):
        out = super().predict(X, raw_score=raw_score,
                              num_iteration=num_iteration)
        out = np.asarray(out)
        if not raw_score and out.ndim == 1 and self.n_classes_ == 2:
            out = np.stack([1.0 - out, out], axis=1)
        return out


class LGBMRanker(LGBMModel):

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 subsample_for_bin=50000, objective="lambdarank", **kwargs):
        super().__init__(boosting_type=boosting_type, num_leaves=num_leaves,
                         max_depth=max_depth, learning_rate=learning_rate,
                         n_estimators=n_estimators, max_bin=max_bin,
                         subsample_for_bin=subsample_for_bin,
                         objective=objective, **kwargs)

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        eval_set = kwargs.get("eval_set")
        if eval_set is not None and kwargs.get("eval_group") is None:
            raise ValueError("Eval_group cannot be None when eval_set "
                             "is not None")
        return super().fit(X, y, group=group, **kwargs)
