"""Plotting utilities (reference python-package/lightgbm/plotting.py):
plot_importance, plot_metric, plot_tree.  plot_tree renders with pure
matplotlib (no graphviz dependency; the reference shells out to graphviz)."""

from __future__ import annotations

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel
from .utils.log import LightGBMError


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster, ax=None, height=0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type="split", max_num_features=None,
                    ignore_zero=True, figsize=None, grid=True, **kwargs):
    """Horizontal-bar feature importances (plotting.py:22-123)."""
    import matplotlib.pyplot as plt

    booster = _to_booster(booster)
    importance = booster.feature_importance(importance_type=importance_type)
    names = booster.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(x), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1 if values else 1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None,
                ax=None, xlim=None, ylim=None,
                title="Metric during training",
                xlabel="Iterations", ylabel="auto",
                figsize=None, grid=True):
    """Metric curves from an evals_result dict or a fitted LGBMModel
    (plotting.py:126-240)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, LGBMModel):
        eval_results = booster.evals_result_
        if eval_results is None:
            raise LightGBMError(
                "eval results are unavailable; pass eval_set to fit()")
    elif isinstance(booster, dict):
        eval_results = booster
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    if dataset_names is None:
        dataset_names = iter(eval_results.keys())
    name_first = None
    num_iters = 0
    for name in dataset_names:
        metrics = eval_results.get(name)
        if not metrics:
            continue
        if metric is None:
            metric_name, results = list(metrics.items())[0]
        else:
            if metric not in metrics:
                raise KeyError(f"No given metric {metric!r} in eval results")
            metric_name, results = metric, metrics[metric]
        num_iters = max(num_iters, len(results))
        ax.plot(range(1, len(results) + 1), results, label=name)
        name_first = name_first or metric_name
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if ylabel == "auto":
        ylabel = name_first
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_tree(booster, ax=None, tree_index=0, figsize=None,
              show_info=None, precision=3, **kwargs):
    """Draw one tree of the model with matplotlib (reference plot_tree,
    plotting.py:281-356, re-rendered without graphviz)."""
    import matplotlib.pyplot as plt

    booster = _to_booster(booster)
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError("tree_index is out of range.")
    tree = model["tree_info"][tree_index]
    names = model["feature_names"]

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize or (12, 8))

    # layout: depth-first x positions of leaves, y = -depth
    positions = {}
    leaf_x = [0.0]

    def layout(node, depth):
        if "leaf_index" in node or "leaf_value" in node and \
                "split_index" not in node:
            x = leaf_x[0]
            leaf_x[0] += 1.0
            positions[id(node)] = (x, -depth)
            return x
        xl = layout(node["left_child"], depth + 1)
        xr = layout(node["right_child"], depth + 1)
        x = (xl + xr) / 2.0
        positions[id(node)] = (x, -depth)
        return x

    root = tree["tree_structure"]
    layout(root, 0)

    def draw(node):
        x, y = positions[id(node)]
        if "split_index" in node:
            feat = node.get("split_feature", 0)
            fname = names[feat] if feat < len(names) else f"f{feat}"
            op = "==" if node.get("decision_type") == "is" else "<="
            thr = node.get("threshold", 0.0)
            label = f"{fname} {op} {thr:.{precision}g}"
            for child in (node["left_child"], node["right_child"]):
                cx, cy = positions[id(child)]
                ax.plot([x, cx], [y, cy], "-", color="gray", zorder=1)
                draw(child)
            box = dict(boxstyle="round", fc="lightblue", ec="steelblue")
        else:
            label = f"leaf: {node.get('leaf_value', 0.0):.{precision}g}"
            if show_info and "leaf_count" in node:
                label += f"\ncount: {node['leaf_count']}"
            box = dict(boxstyle="round", fc="lightyellow", ec="olive")
        ax.text(x, y, label, ha="center", va="center", bbox=box, zorder=2)

    draw(root)
    ax.set_axis_off()
    ax.set_title(f"Tree {tree_index}")
    return ax
