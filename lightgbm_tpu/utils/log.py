"""Leveled logger gated by ``verbose`` (reference include/LightGBM/utils/log.h).

Two embedder-facing extensions over the reference:

- ``warn_once(key, ...)``: one-shot warning for call sites that fire per
  dataset / per iteration (the first occurrence is the information; the
  repeats are noise that drowns real warnings in long runs).
- an opt-in stdlib ``logging`` bridge: ``enable_stdlib_bridge()`` mirrors
  every record into a ``logging.Logger`` regardless of ``verbose`` so
  embedders route/filter/format with their own handlers (the console
  gate below only controls the stderr print).
"""

from __future__ import annotations

import sys
from typing import Optional, Set

_LEVELS = {"fatal": -1, "warning": 0, "info": 1, "debug": 2}
_current_level = 1

_warned_once: Set[str] = set()

_bridge_logger = None
# stderr tag -> stdlib logging level
_STDLIB_LEVELS = {"Fatal": 50, "Warning": 30, "Info": 20, "Debug": 10}


def set_verbosity(verbose: int) -> None:
    global _current_level
    _current_level = int(verbose)


def enable_stdlib_bridge(name: str = "lightgbm_tpu"):
    """Mirror all records into ``logging.getLogger(name)``.  Returns the
    logger.  Filtering is the embedder's: the bridge forwards every record
    at its mapped level, independent of ``set_verbosity``."""
    global _bridge_logger
    import logging
    _bridge_logger = logging.getLogger(name)
    return _bridge_logger


def disable_stdlib_bridge() -> None:
    global _bridge_logger
    _bridge_logger = None


def _emit(tag: str, level: int, msg: str, *args) -> None:
    text = msg % args if args else msg
    if _bridge_logger is not None:
        _bridge_logger.log(_STDLIB_LEVELS.get(tag, 20), "%s", text)
    if level <= _current_level:
        print(f"[LightGBM-TPU] [{tag}] {text}", file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    _emit("Debug", 2, msg, *args)


def info(msg: str, *args) -> None:
    _emit("Info", 1, msg, *args)


def warning(msg: str, *args) -> None:
    _emit("Warning", 0, msg, *args)


def warn_once(key: str, msg: str, *args) -> None:
    """Emit ``warning(msg, *args)`` the first time ``key`` is seen in this
    process; drop repeats.  Use a stable key (parameter name, call site),
    not the formatted message, so reworded repeats still dedupe."""
    if key in _warned_once:
        return
    _warned_once.add(key)
    warning(msg, *args)


def reset_warn_once(prefix: str = "") -> None:
    """Forget warn_once history (tests / long-lived embedders).  With a
    ``prefix``, only keys starting with it are re-armed (diskguard's
    ``reset_disabled`` re-arms the per-sink warnings so a re-armed
    sink's NEXT incident is named again, not just counted)."""
    if prefix:
        for key in [k for k in _warned_once if k.startswith(prefix)]:
            _warned_once.discard(key)
    else:
        _warned_once.clear()


class LightGBMError(Exception):
    """Raised where the reference would Log::Fatal."""


def fatal(msg: str, *args) -> None:
    text = msg % args if args else msg
    if _bridge_logger is not None:
        _bridge_logger.log(_STDLIB_LEVELS["Fatal"], "%s", text)
    raise LightGBMError(text)
