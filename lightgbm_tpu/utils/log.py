"""Leveled logger gated by ``verbose`` (reference include/LightGBM/utils/log.h)."""

from __future__ import annotations

import sys

_LEVELS = {"fatal": -1, "warning": 0, "info": 1, "debug": 2}
_current_level = 1


def set_verbosity(verbose: int) -> None:
    global _current_level
    _current_level = int(verbose)


def _emit(tag: str, level: int, msg: str, *args) -> None:
    if level <= _current_level:
        text = msg % args if args else msg
        print(f"[LightGBM-TPU] [{tag}] {text}", file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    _emit("Debug", 2, msg, *args)


def info(msg: str, *args) -> None:
    _emit("Info", 1, msg, *args)


def warning(msg: str, *args) -> None:
    _emit("Warning", 0, msg, *args)


class LightGBMError(Exception):
    """Raised where the reference would Log::Fatal."""


def fatal(msg: str, *args) -> None:
    text = msg % args if args else msg
    raise LightGBMError(text)
