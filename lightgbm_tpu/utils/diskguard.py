"""Disk-full-safe write sinks: the observability layer must never be
the thing that kills the job it observes.

Before this module, a full disk crashed a training run from *inside a
telemetry writer*: the events JSONL, the compile ledger, the trace
export, the quarantine sink and the serve state file all called a bare
``open(..., "w")`` and let ``ENOSPC`` propagate into the boosting loop.
This module is the single funnel every non-artifact write path routes
through (enforced by tools/graftcheck's ``resource`` rule family —
a bare write-mode ``open`` outside this module / ``snapshot.py`` /
``testing/`` is a finding):

- :func:`classify_oserror` names the resource-exhaustion class of an
  ``OSError``: ``disk_full`` (ENOSPC), ``quota_exceeded`` (EDQUOT),
  ``read_only_fs`` (EROFS), ``fd_exhausted`` (EMFILE/ENFILE), and the
  catch-all ``io_error`` — diagnostics name the class, not just errno.
- :class:`GuardedWriter` wraps a streaming text sink (events JSONL,
  quarantine records).  Policy per sink:

  * ``disable`` (telemetry default): the first classified write failure
    warns ONCE (naming the sink, the path and the class), counts into
    ``sink_write_errors_total`` / ``sink_write_errors_<sink>``, and the
    sink *disables itself* — later writes are dropped silently and the
    run continues;
  * ``fatal``: the failure raises :class:`SinkWriteError` (a
    ``LightGBMError``) naming the sink — for outputs whose loss IS the
    job (the CLI ``task=predict`` stream).

  The process default is ``disable`` and the ``sink_error_policy``
  config param can flip every policy-unpinned sink — the events
  stream, the compile ledger, the quarantine sink — to ``fatal``
  (:func:`set_default_policy`).  Sinks with pinned semantics are not
  flipped: the trace exporter always disables itself, snapshots and
  the serve state file keep last-good + retry, artifacts are always
  fatal.
- :func:`append_line` is the one-shot append flavor (compile ledger);
  a sink disabled once stays disabled for the process run
  (:func:`reset_disabled` re-arms, for tests and fresh runs).
- :func:`write_file_atomic` is the tmp + fsync + ``os.replace``
  protocol (snapshots, serve state) with the failure semantics the
  crash-safety story needs: on ANY write error the orphaned ``.tmp`` is
  removed and the last-good destination file is left untouched, so the
  caller can keep serving the previous state and retry on its next
  interval.
- :func:`artifact_write` / :func:`write_artifact_atomic` wrap writes
  whose failure must FAIL the operation: the error is still classified
  and re-raised as a named :class:`SinkWriteError` instead of a bare
  ``OSError`` backtrace.  Streaming outputs (the CLI ``task=predict``
  result) use the context-manager form; whole-file artifacts (model
  file, binary dataset) use the atomic form so a failed save also
  keeps the previous good file instead of truncating it in place.

Fault injection (``testing/faults.py``): every guarded write passes
through one module-level hook (:func:`_maybe_inject`), so
``fail_writes``/``disk_full_after`` can throw *real* ``OSError`` s
through the *real* call stacks — the tests prove the recovery paths,
not mocks of them.

Everything here is host-side by construction: no jax import, zero XLA
programs (compile-ledger-pinned by tests/test_resource_chaos.py).
"""

from __future__ import annotations

import errno
import os
from typing import Any, Callable, Dict, Optional, Set

from . import log
from .log import LightGBMError

#: errno -> resource-exhaustion class (the ``sink_write_errors_<class>``
#: vocabulary lives on the SINK name, not the class; the class lands in
#: the diagnostic text)
ERRNO_CLASSES: Dict[int, str] = {
    errno.ENOSPC: "disk_full",
    errno.EDQUOT: "quota_exceeded",
    errno.EROFS: "read_only_fs",
    errno.EMFILE: "fd_exhausted",
    errno.ENFILE: "fd_exhausted",
}

POLICIES = ("disable", "fatal")

# process-wide default for sinks that do not pin a policy; the
# ``sink_error_policy`` config param sets it per run (engine.train/CLI)
_default_policy = "disable"

# sinks that hit a classified error under policy=disable stay off for
# the rest of the process (re-opening a full disk every iteration would
# turn one incident into a warning flood and an IO busy-loop)
_disabled_sinks: Set[str] = set()

# fault-injection seam (testing/faults.py fail_writes/disk_full_after):
# called with (path, nbytes) before every guarded write; raises to
# inject.  None = no injection.
_fault_hook: Optional[Callable[[str, int], None]] = None


class SinkWriteError(LightGBMError):
    """A guarded sink's write failed.  Carries the sink name, the path
    and the classification so callers (the CLI predict stream, tests)
    can report without re-parsing the message."""

    def __init__(self, sink: str, path: str, classification: str,
                 cause: BaseException):
        super().__init__(
            f"sink {sink!r} ({path}): {classification}: {cause} — "
            f"see docs/FAULT_TOLERANCE.md §Resource exhaustion")
        self.sink = str(sink)
        self.path = str(path)
        self.classification = str(classification)
        self.cause = cause


def classify_oserror(exc: BaseException) -> str:
    """Resource-exhaustion class of an ``OSError`` (``io_error`` for
    anything without a named class — a guarded sink must degrade on
    those too; an unclassified crash from inside telemetry is exactly
    the failure mode this layer removes)."""
    return ERRNO_CLASSES.get(getattr(exc, "errno", None) or -1, "io_error")


def set_default_policy(policy: Optional[str]) -> str:
    """Set the process default sink policy (the ``sink_error_policy``
    param).  ``None``/empty keeps the current default.  Returns the
    effective default."""
    global _default_policy
    if policy:
        policy = str(policy)
        if policy not in POLICIES:
            raise LightGBMError(
                f"Unknown sink_error_policy {policy!r} "
                f"(expected one of {', '.join(POLICIES)})")
        _default_policy = policy
    return _default_policy


def default_policy() -> str:
    return _default_policy


def disabled_sinks() -> Set[str]:
    """Sinks currently disabled by a classified write error (copy)."""
    return set(_disabled_sinks)


def reset_disabled() -> None:
    """Re-arm every disabled sink (tests; a fresh run on a fresh disk).
    The per-sink warn-once keys are re-armed too: a re-armed sink's
    next incident must be NAMED in a warning again, not just counted —
    the 'every disabled sink named' contract holds per re-arm, not
    once per process."""
    _disabled_sinks.clear()
    log.reset_warn_once("sink_write_")


def _maybe_inject(path: str, nbytes: int) -> None:
    hook = _fault_hook
    if hook is not None:
        hook(str(path), int(nbytes))


def _note_sink_error(sink: str, path: str, exc: BaseException,
                     action: str = "the sink is disabled for the rest "
                     "of this run — the job it observes continues"
                     ) -> str:
    """Count + warn one classified sink write failure; returns the
    classification.  Shared by every policy so the
    ``sink_write_errors_*`` counters are the chaos suite's ground truth
    regardless of what happens next (disable / fatal / retry)."""
    from .. import obs
    cls = classify_oserror(exc)
    obs.inc("sink_write_errors_total")
    obs.inc("sink_write_errors_" + str(sink))
    log.warn_once(
        f"sink_write_{sink}",
        "sink %r (%s) hit %s (%s); %s (docs/FAULT_TOLERANCE.md "
        "§Resource exhaustion)", sink, path, cls, exc, action)
    return cls


#: public alias: callers owning their own retry/degrade semantics (the
#: snapshot layer, the serve state file) still count and warn through
#: the one funnel
note_sink_error = _note_sink_error


class GuardedWriter:
    """Streaming text sink with classified-failure containment.

    Line-buffered by default so committed records survive a crash
    without an explicit flush; ``flush()`` is still honored for sinks
    with a flush cadence (``events_flush_every``).  ``write()`` returns
    True when the text reached the OS, False when the sink is disabled
    (policy ``disable`` after a failure) — callers that track a written
    count (``EventRecorder.events_written``) count the Trues.
    """

    def __init__(self, path: str, sink: str,
                 policy: Optional[str] = None, mode: str = "w",
                 buffering: int = 1):
        self.path = str(path)
        self.sink = str(sink)
        self.policy = policy or _default_policy
        if self.policy not in POLICIES:
            raise LightGBMError(
                f"Unknown sink policy {self.policy!r} for sink "
                f"{self.sink!r} (expected one of {', '.join(POLICIES)})")
        self._mode = mode
        self._buffering = buffering
        self._fh: Optional[Any] = None
        self._closed = False
        self._opened = False

    # -- state ----------------------------------------------------------
    @property
    def disabled(self) -> bool:
        return self.sink in _disabled_sinks

    @property
    def closed(self) -> bool:
        return self._closed

    # -- failure funnel --------------------------------------------------
    def _fail(self, exc: BaseException) -> bool:
        cls = _note_sink_error(self.sink, self.path, exc)
        _disabled_sinks.add(self.sink)
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        if self.policy == "fatal":
            raise SinkWriteError(self.sink, self.path, cls, exc) from exc
        return False

    def _ensure_open(self) -> bool:
        if self._fh is not None:
            return True
        if self._closed or self.disabled:
            return False
        try:
            _maybe_inject(self.path, 0)
            directory = os.path.dirname(self.path)
            if directory:
                os.makedirs(directory, exist_ok=True)
            self._fh = open(self.path, self._mode,
                            buffering=self._buffering)
            self._opened = True
            return True
        except OSError as exc:
            return self._fail(exc)

    # -- the sink API ----------------------------------------------------
    def touch(self) -> bool:
        """Eagerly create/truncate the file (streams whose consumers
        expect the file to exist even before the first record)."""
        return self._ensure_open()

    def write(self, text: str) -> bool:
        if not self._ensure_open():
            return False
        try:
            _maybe_inject(self.path, len(text))
            self._fh.write(text)
            return True
        except OSError as exc:
            return self._fail(exc)

    def flush(self) -> bool:
        if self._fh is None:
            return False
        try:
            _maybe_inject(self.path, 0)
            self._fh.flush()
            return True
        except OSError as exc:
            return self._fail(exc)

    def close(self) -> None:
        self._closed = True
        if self._fh is None:
            return
        try:
            self._fh.close()
        except OSError as exc:
            self._fh = None
            try:
                self._fail(exc)
            except SinkWriteError:
                raise
            return
        self._fh = None

    def __enter__(self) -> "GuardedWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def append_line(path: str, text: str, sink: str,
                policy: Optional[str] = None) -> bool:
    """Append one line to ``path`` under guarded semantics (the compile
    ledger's one-line-per-event shape: open, write, close — each event
    is durable the moment ``record`` returns).  Returns False when the
    sink is disabled or the write failed under policy ``disable``."""
    policy = policy or _default_policy
    sink = str(sink)
    if sink in _disabled_sinks:
        return False
    try:
        _maybe_inject(str(path), len(text) + 1)
        with open(path, "a") as fh:
            fh.write(text + "\n")
        return True
    except OSError as exc:
        cls = _note_sink_error(sink, str(path), exc)
        _disabled_sinks.add(sink)
        if policy == "fatal":
            raise SinkWriteError(sink, str(path), cls, exc) from exc
        return False


def write_text(path: str, text: str, sink: str) -> str:
    """Whole-file text write that raises a classified
    :class:`SinkWriteError` on failure (callers own the policy — the
    trace exporter catches it to disable itself, artifact savers let it
    surface as the operation's named error)."""
    try:
        _maybe_inject(str(path), len(text))
        with open(path, "w") as fh:
            fh.write(text)
        return str(path)
    except OSError as exc:
        cls = _note_sink_error(sink, str(path), exc,
                               action="the write is abandoned")
        raise SinkWriteError(sink, str(path), cls, exc) from exc


def write_file_atomic(path: str, blob: bytes, sink: str,
                      fsync: bool = True) -> str:
    """The tmp + fsync + ``os.replace`` protocol with last-good
    semantics: on ANY failure the orphaned ``.tmp`` is removed and the
    destination file is left exactly as it was, so a reader always sees
    either the previous good file or the new one — never a torn write,
    never an accumulating ``.tmp`` per retry.  Raises the original
    ``OSError`` (callers classify via :func:`classify_oserror`; the
    snapshot layer turns it into warn + retry-next-interval)."""
    path = str(path)
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    try:
        _maybe_inject(tmp, len(blob))
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        # keep the last-good destination; never leave the torn tmp
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def write_artifact_atomic(path: str, blob: bytes, sink: str) -> str:
    """Atomic whole-file ARTIFACT write (the model file's one-string
    save): tmp + ``os.replace`` with artifact failure semantics — a
    classified, named :class:`SinkWriteError` instead of a bare
    ``OSError``.  The last-good destination survives any failure: an
    ENOSPC halfway through ``save_model_to_file`` must not destroy the
    previous good model by truncating it in place.  Streaming
    producers (``np.savez`` archives) use
    ``artifact_write(..., atomic=True)`` directly instead of staging
    the whole blob in host memory."""
    with artifact_write(path, sink, mode="wb", atomic=True) as fh:
        fh.write(blob)
    return str(path)


class _ArtifactHandle:
    """File proxy for :func:`artifact_write`: every write passes the
    fault-injection seam so the chaos suite covers artifact paths too."""

    def __init__(self, fh, path: str):
        self._fh = fh
        self._path = path

    def write(self, data) -> int:
        _maybe_inject(self._path, len(data))
        return self._fh.write(data)

    def __getattr__(self, name: str):
        # seek/tell/fileno/flush pass through (np.savez writes a zip
        # archive and needs the full file protocol)
        return getattr(self._fh, name)


class artifact_write:
    """Context manager for STREAMING artifact writes: a write failure
    must fail the operation — but as a named, classified
    :class:`SinkWriteError` (counted into ``sink_write_errors_*`` like
    every other guarded failure), not a bare ``OSError`` backtrace.
    ``atomic=False`` writes the destination in place (the CLI predict
    output — an append-as-you-go stream whose partial rows are part of
    the diagnosis); ``atomic=True`` streams into ``<path>.tmp`` and
    ``os.replace`` s on clean exit, so a failed save keeps the previous
    good file (model file, binary dataset).  Usage::

        with diskguard.artifact_write(path, "predict_output") as fh:
            fh.write(text)
    """

    def __init__(self, path: str, sink: str, mode: str = "w",
                 atomic: bool = False):
        self.path = str(path)
        self.sink = str(sink)
        self.mode = mode
        self.atomic = bool(atomic)
        self._target = self.path + ".tmp" if atomic else self.path
        self._fh = None

    def _raise(self, exc: OSError) -> None:
        if self.atomic:
            # keep the last-good destination; never leave the torn tmp
            try:
                os.unlink(self._target)
            except OSError:
                pass
        cls = _note_sink_error(
            self.sink, self.path, exc,
            action="the write is abandoned" +
                   ("; the previous file is kept" if self.atomic else
                    " — the operation fails with a named error"))
        raise SinkWriteError(self.sink, self.path, cls, exc) from exc

    def __enter__(self) -> _ArtifactHandle:
        try:
            _maybe_inject(self._target, 0)
            directory = os.path.dirname(self.path)
            if directory:
                os.makedirs(directory, exist_ok=True)
            self._fh = open(self._target, self.mode)
        except OSError as exc:
            self._raise(exc)
        return _ArtifactHandle(self._fh, self._target)

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._fh is None:
            return
        try:
            self._fh.close()
        except OSError as cexc:
            if exc_type is None:
                self._raise(cexc)
            # the with-body's own error wins (wrapped below if OSError):
            # a buffered-flush failure at close usually shares the
            # body's root cause, and two errors must not hide the first
        finally:
            self._fh = None
        if exc_type is not None:
            if isinstance(exc, OSError):
                self._raise(exc)
            if self.atomic:
                # non-OSError body failure (a serializer bug): still
                # sweep the torn tmp, let the original error propagate
                try:
                    os.unlink(self._target)
                except OSError:
                    pass
            return
        if self.atomic:
            try:
                os.replace(self._target, self.path)
            except OSError as rexc:
                self._raise(rexc)


def probe_writable(directory: str, sink: str) -> bool:
    """Best-effort writability probe of ``directory`` (the compile
    cache pre-flight): True when a probe file can be created and
    removed.  Classified failures warn once and return False — the
    caller degrades (disables the cache) instead of letting a full disk
    surface later as an opaque error from inside XLA's cache writer."""
    probe = os.path.join(str(directory), ".lgbt_write_probe")
    try:
        os.makedirs(str(directory), exist_ok=True)
        _maybe_inject(probe, 1)
        with open(probe, "w") as fh:
            fh.write("x")
        os.unlink(probe)
        return True
    except OSError as exc:
        _note_sink_error(sink, str(directory), exc)
        return False
