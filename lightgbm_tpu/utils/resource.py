"""Resource-exhaustion containment: HBM admission control and
device-OOM diagnosis (docs/FAULT_TOLERANCE.md §Resource exhaustion).

Two halves, both host-side by construction (no new XLA programs —
compile-ledger-pinned by tests/test_resource_chaos.py):

**Admission control** (:func:`admit`): ``models/gbdt.py`` hands the gate
its per-component HBM estimate (``estimate_train_memory``) and the
device budget; under ``memory_policy=fail_fast`` an over-budget config
refuses up front with a :class:`MemoryBudgetExceeded` carrying the
per-component table, and under ``memory_policy=degrade`` the booster
walks a documented ladder of footprint reductions — each step applied
with one ``warn_once`` and a ``resource_degrade_total`` /
``resource_degrade_<step>`` counter — refusing only when the ladder
bottoms out still over budget.  The ladder itself lives in gbdt.py
(the steps mutate booster construction state); this module owns the
accounting, the table rendering and the refusal.

**OOM diagnosis** (:func:`reraise_if_oom`): ``obs.InstrumentedJit`` is
the single dispatch choke point for every jitted program in the repo,
and it routes any ``RESOURCE_EXHAUSTED`` escaping XLA through here: the
opaque allocator backtrace becomes a :class:`DeviceOOM` (a
``LightGBMError``) naming the PROGRAM that allocated, the abstract
shapes of the call that triggered it, a memwatch snapshot of what the
device held, and the last admission table (:func:`set_budget_table` —
what the gate *predicted*).  On TPU an OOM must read like a diagnosis,
not a backtrace.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from . import log
from .log import LightGBMError

#: the degrade ladder's step names, in application order (documented in
#: docs/FAULT_TOLERANCE.md §Resource exhaustion — the order is part of
#: the contract: cheapest/least-lossy reduction first)
DEGRADE_STEPS = ("score_donation", "hist_cache", "row_pad")

MEMORY_POLICIES = ("fail_fast", "degrade")

# last admission table published by a memory gate (models/gbdt.py):
# the OOM diagnosis folds it in so "what the gate predicted" sits next
# to "what the allocator saw"
_budget_table: Optional[Dict[str, int]] = None
_budget_context: str = ""


class MemoryBudgetExceeded(LightGBMError):
    """The admission gate refused a configuration: the estimated device
    footprint exceeds the budget (after the degrade ladder, under
    ``memory_policy=degrade``).  The message carries the per-component
    table; ``estimate`` / ``limit`` / ``steps_taken`` are machine-
    readable for tests and tooling."""

    def __init__(self, msg: str, estimate: Dict[str, int], limit: int,
                 steps_taken: Tuple[str, ...] = ()):
        super().__init__(msg)
        self.estimate = dict(estimate)
        self.limit = int(limit)
        self.steps_taken = tuple(steps_taken)


class DeviceOOM(LightGBMError):
    """A jitted program died in XLA allocation (``RESOURCE_EXHAUSTED``).
    Raised by :func:`reraise_if_oom` with the program name, the abstract
    shapes of the triggering call, a memwatch snapshot and the last
    admission table — the diagnosis the raw backtrace never gives."""

    def __init__(self, msg: str, program: str, shapes: str):
        super().__init__(msg)
        self.program = str(program)
        self.shapes = str(shapes)


def format_table(est: Dict[str, int]) -> str:
    """Render a per-component byte table as one diagnostic line:
    ``bins_device=12MB, histogram_cache=340MB, ... (total=400MB)``."""
    parts = [f"{k}={v / (1 << 20):.1f}MB" for k, v in est.items()
             if k != "total"]
    return (", ".join(parts)
            + f" (total={est.get('total', 0) / (1 << 20):.1f}MB)")


def set_budget_table(est: Optional[Dict[str, int]],
                     context: str = "") -> None:
    """Publish the most recent admission estimate so an OOM diagnosis
    can show what the gate predicted.  ``None`` clears it."""
    global _budget_table, _budget_context
    _budget_table = dict(est) if est else None
    _budget_context = str(context)


def budget_table() -> Optional[Dict[str, int]]:
    return dict(_budget_table) if _budget_table else None


def check_memory_policy(policy: str) -> str:
    policy = str(policy or "fail_fast")
    if policy not in MEMORY_POLICIES:
        raise LightGBMError(
            f"Unknown memory_policy {policy!r} "
            f"(expected one of {', '.join(MEMORY_POLICIES)})")
    return policy


def note_degrade(step: str, saved_bytes: int, detail: str) -> None:
    """Account one applied degrade-ladder step: warn ONCE per step per
    process and bump the ``resource_degrade_total`` /
    ``resource_degrade_<step>`` counters."""
    from .. import obs
    if step not in DEGRADE_STEPS:
        raise ValueError(f"unknown degrade step {step!r}")
    obs.inc("resource_degrade_total")
    obs.inc("resource_degrade_" + step)
    log.warn_once(
        f"resource_degrade_{step}",
        "memory_policy=degrade: %s (saves ~%.1fMB). %s",
        step, saved_bytes / (1 << 20), detail)


def refuse(est: Dict[str, int], limit: int, what: str,
           steps_taken: Tuple[str, ...] = ()) -> "MemoryBudgetExceeded":
    """Build (and return — caller raises) the named admission refusal
    with the per-component table."""
    tried = (f"  Degrade ladder already applied: "
             f"{', '.join(steps_taken)}." if steps_taken else "")
    return MemoryBudgetExceeded(
        f"estimated {what} memory {est['total'] / (1 << 20):.0f}MB "
        f"exceeds the device budget {limit / (1 << 20):.0f}MB "
        f"({format_table(est)}).{tried}  The dense-only design has no "
        f"sparse spill (SURVEY §7.2): shrink num_leaves/max_bin or "
        f"train on fewer rows (memory_policy=degrade walks the "
        f"footprint-reduction ladder first; docs/FAULT_TOLERANCE.md "
        f"§Resource exhaustion).",
        est, limit, steps_taken)


# ---------------------------------------------------------------------------
# device-OOM classification + diagnosis (the InstrumentedJit boundary)

#: substrings that mark an exception as an XLA allocation failure.
#: XLA raises XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory ...");
#: some backends spell it "Resource exhausted".
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory")


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when ``exc`` is an XLA/device allocation failure.  String
    classification is deliberate: the concrete exception class moved
    across jax releases (``XlaRuntimeError`` lives in different modules)
    and an errno-style code is not exposed."""
    if isinstance(exc, MemoryError):
        return True
    text = str(exc)
    return any(m in text for m in _OOM_MARKERS) and not isinstance(
        exc, (KeyboardInterrupt, SystemExit))


def _memwatch_snapshot() -> str:
    """One-line device/host residency snapshot for the OOM diagnosis.
    Best-effort: a diagnosis path must never raise its own error."""
    try:
        from ..obs import memwatch
        s = memwatch.sample()
    except Exception:
        return "memwatch unavailable"
    parts: List[str] = []
    if s.get("live_bytes", -1) >= 0:
        parts.append(f"live_arrays={s['live_arrays']} "
                     f"live_bytes={s['live_bytes'] / (1 << 20):.1f}MB")
    if "device_bytes_in_use" in s:
        parts.append("device_in_use="
                     f"{s['device_bytes_in_use'] / (1 << 20):.1f}MB")
    if "device_peak_bytes" in s:
        parts.append("device_peak="
                     f"{s['device_peak_bytes'] / (1 << 20):.1f}MB")
    return " ".join(parts) or "memwatch saw no device stats"


def reraise_if_oom(exc: BaseException, program: str, shapes: str) -> None:
    """Called from ``obs.InstrumentedJit`` when a dispatch raised: if
    the failure is a device allocation failure, re-raise it as a
    :class:`DeviceOOM` naming the program, its abstract shapes, a
    memwatch snapshot and the last admission table.  Anything else
    returns (the caller re-raises the original)."""
    if not is_resource_exhausted(exc):
        return
    from .. import obs
    obs.inc("device_oom_total")
    obs.inc("device_oom_" + _sanitize(program))
    table = ("admission estimate: " + format_table(_budget_table)
             + (f" [{_budget_context}]" if _budget_context else "")
             if _budget_table else
             "admission estimate: none published (prediction-only or "
             "pre-gate allocation)")
    first = str(exc).splitlines()[0][:300]
    raise DeviceOOM(
        f"device out of memory while dispatching program "
        f"{program!r} over shapes [{shapes}].  {table}.  "
        f"memwatch: {_memwatch_snapshot()}.  XLA said: {first}.  "
        f"Shrink num_leaves/max_bin/rows, or set memory_policy=degrade "
        f"to let the admission gate walk the footprint ladder "
        f"(docs/FAULT_TOLERANCE.md §Resource exhaustion).",
        program, shapes) from exc


def _sanitize(name: str) -> str:
    from ..obs import phases
    return phases.sanitize(name)
