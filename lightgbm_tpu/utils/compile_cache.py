"""Persistent XLA compilation cache + shared training row buckets.

BENCH_r02-r05 measured 34-321 s of XLA compiles per training run for
IDENTICAL code — the warmup tax the compile ledger (obs/compile_ledger.py)
made attributable in round 6.  Two levers kill most of it, both owned
here so every entry point (engine.train, the CLI, bench.py's two modes)
configures them identically instead of copy-pasting ``jax.config.update``
blocks:

- ``setup()`` points JAX's persistent compilation cache at a directory,
  so a repeated or resumed run loads compiled executables from disk
  instead of re-invoking XLA.  Precedence: the
  ``LIGHTGBM_TPU_COMPILE_CACHE`` env var wins over the
  ``compile_cache_dir`` config param, which wins over JAX's own
  ``JAX_COMPILATION_CACHE_DIR``, which wins over the baked-in default
  (``/tmp/lightgbm_tpu_jax_cache``).  The cache is ON by default — a
  value of ``off``/``none``/``0`` disables it.

- ``bucket_rows()`` maps a row count onto a small ladder of shared
  shapes, the training-side counterpart of ``serve/batcher.py``'s
  ``BucketLadder``: every jitted training program specializes on N, so
  without bucketing each dataset size is a fresh compile of the most
  expensive programs in the repo (``train_step``/``grow_tree``).
  Training pads rows up to the bucket with zero ``row_weight`` (exactly
  how bagging already excludes rows): histogram digit sums stay exact
  (int32, pad digits zero) so splits match the unpadded run, and only
  the f32 leaf-total reductions re-associate — the same last-bit wiggle
  any row-count change causes.  In exchange nearby row counts share one
  compiled program — in-process across boosters, and across processes
  via the persistent cache.  The serve ladder's pure powers of
  two would pad up to 2x; training rows are heavier than serve batches,
  so this ladder keeps ``ROW_BUCKET_BITS`` mantissa bits (bucket =
  next multiple of ``2^(bitlen(n-1) - bits)``), bounding pad overhead at
  ``2^(1-bits)`` (6.25% worst case, ~1.6% typical, for the default 5
  bits) while still collapsing the shape universe to ~32 buckets per
  octave.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_DIR = "LIGHTGBM_TPU_COMPILE_CACHE"
DEFAULT_CACHE_DIR = "/tmp/lightgbm_tpu_jax_cache"

# Below this compile time XLA skips the disk write; 1.0 s keeps every
# program that meaningfully contributes to the warmup tax (the default
# of jax's flag misses mid-size programs that add up across a run).
MIN_COMPILE_SECONDS = 1.0

_OFF_VALUES = {"off", "none", "0", "false", "disabled"}

# Last directory actually applied (None = disabled / never configured);
# setup() is idempotent and cheap, so every entry point just calls it.
_configured_dir: Optional[str] = None


def resolve_dir(cache_dir: Optional[str] = None) -> Optional[str]:
    """Effective cache directory for a run, or None when disabled.

    ``LIGHTGBM_TPU_COMPILE_CACHE`` env > ``cache_dir`` argument (the
    ``compile_cache_dir`` param) > ``JAX_COMPILATION_CACHE_DIR`` env >
    ``DEFAULT_CACHE_DIR``.  Any level may disable with an off-value."""
    for value in (os.environ.get(ENV_DIR, ""),
                  str(cache_dir or ""),
                  os.environ.get("JAX_COMPILATION_CACHE_DIR", ""),
                  DEFAULT_CACHE_DIR):
        value = value.strip()
        if not value:
            continue
        return None if value.lower() in _OFF_VALUES else value
    return None  # pragma: no cover - DEFAULT_CACHE_DIR is never empty


def setup(cache_dir: Optional[str] = None,
          min_compile_seconds: float = MIN_COMPILE_SECONDS) -> Optional[str]:
    """Configure JAX's persistent compilation cache; returns the
    effective directory (None = disabled).  Idempotent — safe to call
    from every entry point; must run before the first compilation to
    cover it (later calls still cover later compiles)."""
    global _configured_dir
    path = resolve_dir(cache_dir)
    if path is not None:
        # pre-flight writability (utils/diskguard.py): a full/read-only
        # cache volume must degrade to "no persistent cache" with one
        # warning, not surface later as an opaque error from inside
        # XLA's own cache writer mid-compile
        from . import diskguard, log
        if not diskguard.probe_writable(path, sink="compile_cache"):
            log.warn_once(
                "compile_cache_unwritable",
                "compile cache dir %s is not writable; the persistent "
                "XLA cache is DISABLED for this run (every process pays "
                "full compiles)", path)
            path = None
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        if path is not None:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_seconds))
    except Exception as exc:  # pragma: no cover - jax without the flags
        from . import log
        log.warn_once("compile_cache_setup",
                      "persistent compilation cache unavailable on this "
                      "jax build (%s); every run pays full compiles", exc)
        _configured_dir = None
        return None
    _configured_dir = path
    return path


def configured_dir() -> Optional[str]:
    """Directory applied by the last setup() call (None = disabled)."""
    return _configured_dir


ROW_BUCKET_BITS = 5


def bucket_rows(n: int, bits: int = ROW_BUCKET_BITS) -> int:
    """Smallest shared-shape bucket >= n: the next multiple of
    ``2^(bitlen(n-1) - bits)``.  Keeps ``bits`` mantissa bits, so pad
    overhead is bounded by ``2^(1-bits)`` (6.25% worst case at the
    default 5) and all row counts in an octave collapse onto at most
    ``2^bits`` shapes."""
    n = int(n)
    if n <= 1:
        return max(n, 0)
    step = 1 << max((n - 1).bit_length() - int(bits), 0)
    return -(-n // step) * step
