"""TIMETAG-style phase profiling.

The reference compiles scoped wall-clock accumulators under #ifdef TIMETAG
(serial_tree_learner.cpp:10-37: init_train/init_split/hist/find_split/
split; gbdt.cpp:20-59: boosting/train_score/valid_score/metric/bagging/
tree) and prints the totals at shutdown.  Here the same phase taxonomy is
kept, adapted to an async device:

- ``scope(name, sync=...)`` — host wall-clock accumulator.  Enabled by
  LIGHTGBM_TPU_TIMETAG=1; when ``sync`` is given the scope blocks on that
  device value before stopping the clock, so device time is attributed to
  the phase that produced it (this serializes the pipeline exactly like
  the reference's TIMETAG builds perturb theirs — a measurement mode, not
  a production mode).
- jitted code carries ``jax.named_scope`` annotations with the same phase
  names (ops/grow.py), so device-side traces captured with
  jax.profiler.trace() break down by phase without any re-run.
"""

from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict

from . import log

ENABLED = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")


def enable(on: bool = True) -> None:
    """Programmatic switch (the env var only sets the initial state)."""
    global ENABLED
    ENABLED = on

_acc: Dict[str, float] = defaultdict(float)
_cnt: Dict[str, int] = defaultdict(int)


def add(name: str, seconds: float) -> None:
    """Accumulate an externally measured duration under ``name`` —
    ``obs.span`` feeds its measurements here when TIMETAG is enabled so
    the two instruments share one account."""
    _acc[name] += seconds
    _cnt[name] += 1


class _Sync:
    """Collects device values to block on when the scope closes."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def sync(self, value) -> None:
        self.value = value


class _NoopSync:
    """Disabled mode: must NOT retain the passed device buffers (a stored
    reference would pin grad/score arrays in HBM for the process
    lifetime)."""

    __slots__ = ()

    def sync(self, value) -> None:
        pass


_NOOP = _NoopSync()


@contextmanager
def scope(name: str):
    """Accumulate wall time under ``name``.  The yielded object's
    ``sync(x)`` registers device values to block on before the clock
    stops, so async device work is attributed to the phase that produced
    it."""
    if not ENABLED:
        yield _NOOP
        return
    s = _Sync()
    t0 = time.perf_counter()
    try:
        yield s
    finally:
        if s.value is not None:
            # counted sync (obs/devprof.py): this scope's serialization
            # is visible in the profile it distorts
            from ..obs import devprof
            devprof.sync(s.value, source=name)
        dt = time.perf_counter() - t0
        _acc[name] += dt
        _cnt[name] += 1
        # mirror into the per-phase wall-time histogram (obs/spans.py):
        # under the serializing TIMETAG mode, scope sites populate the
        # same distribution series that obs.span feeds, so the phase
        # account has one metrics namespace regardless of instrument
        from ..obs import registry, spans
        registry.observe(spans._series(name), dt)


def get_timings() -> Dict[str, float]:
    return dict(_acc)


def reset() -> None:
    _acc.clear()
    _cnt.clear()


def report() -> None:
    """Print accumulated phase costs (GBDT::~GBDT's 'xxx costs:' lines)."""
    for name in sorted(_acc):
        log.info("%s costs: %f (%d calls)", name, _acc[name], _cnt[name])


@atexit.register
def _report_at_exit() -> None:
    if ENABLED and _acc:
        report()
