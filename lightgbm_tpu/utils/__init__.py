from . import log  # noqa: F401


def coerce_bool(value) -> bool:
    """The repo's single bool-coercion rule (config params, env flags):
    shared by config.py and the obs switches so CLI spellings and env
    vars can never parse differently."""
    if isinstance(value, bool):
        return value
    if isinstance(value, (int, float)):
        return bool(value)
    return str(value).strip().lower() in ("true", "1", "yes", "y", "t", "+")
