"""graftcheck: repo-wide static analysis for the invariants the test
suite can only catch after the fact.

PRs 7-11 made this reproduction a genuinely concurrent system — a
serving fleet, per-replica micro-batchers, a UDP heartbeat mesh, a
process-wide shared-jit registry — whose correctness rests on invariants
nothing used to check statically:

- locks are acquired in one global order, and nothing blocks (thread
  joins, sockets, subprocesses, device dispatch, sleeps) while holding
  one;
- state shared between threads is touched under the lock that guards it
  everywhere, not just in the convenient call sites;
- functions handed to a jit entry point carry no host side effects that
  would bake at trace time (registry counters, wall clocks, np.random,
  ``.item()`` host syncs);
- every repo jit is routed through ``obs.instrumented_jit`` /
  ``CountingJit`` so the compile ledger has no blind spots, and no
  call site hands jax a fresh lambda per call (the function-identity
  cache defeat PR 9 had to work around);
- threads are daemonized or joined, sockets/handles have a close path,
  and deadline/timeout math never reads the wall clock;
- the host/device phase taxonomy stays in sync with ``obs/phases.py``
  (the former ``tools/lint_phase_scopes.py``, now a rule family here);
- every ``config.py`` parameter is documented (``_param_descriptions``)
  and rendered in ``docs/Parameters.md``.

Run ``python -m tools.graftcheck`` from the repo root (exit 1 on any
unsuppressed finding), or as the tier-1 test ``tests/test_graftcheck.py``.
Intentional exceptions are waived inline with
``# graftcheck: disable=<rule>`` — visible, counted, and reported so
waivers cannot accumulate silently.  See docs/STATIC_ANALYSIS.md for the
rule catalogue.
"""

from .core import (Finding, ModuleInfo, Project, Report,  # noqa: F401
                   RULE_FAMILIES, run_checks)
