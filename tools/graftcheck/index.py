"""Cross-module index shared by the lock and lifecycle rule families.

Built once per run from the :class:`~.core.Project`'s already-parsed
ASTs, this maps the concurrency vocabulary of the codebase:

- every named ``threading.Lock``/``RLock``/``Condition`` (class attr or
  module global), with ``Condition(self._lock)`` aliased onto the lock
  it wraps — ``with self._cond`` and ``with self._lock`` are the same
  runtime lock in ``serve/batcher.py``;
- every ``threading.Thread``/``Event`` and ``socket.socket``/HTTP-server
  attribute (the lifecycle rules' subjects);
- per-function summaries: which locks a function acquires (lexically,
  via ``with``), every call made and the lock stack held at that point,
  every blocking operation (thread join, socket I/O, subprocess,
  ``time.sleep``, ``Event.wait``, device dispatch), and every attribute
  write with its held-lock context;
- a best-effort intra-repo call graph (``self.m()``, same-module
  functions, imported modules' functions, and receiver-name matching
  like ``fleet._cond`` -> ``Fleet``), over which ``may_acquire`` /
  ``may_block`` summaries are propagated to a fixed point.

Resolution is deliberately conservative: an expression that cannot be
confidently mapped to a lock/class/function participates in NO finding.
A lint that guesses produces noise; noise gets suppressed wholesale;
and a wholesale-suppressed lint protects nothing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

# blocking-call vocabulary -------------------------------------------------

_SOCKET_METHODS = {"recv", "recvfrom", "sendto", "accept", "connect",
                   "send", "sendall"}
_SUBPROCESS_FUNCS = {"run", "call", "check_call", "check_output", "Popen"}
# time.sleep under a lock below this constant duration is tolerated
# (sub-10ms backoff spins); unknown/larger durations are findings
SLEEP_THRESHOLD_S = 0.01
# calls that dispatch device work (an XLA predict/compile can take
# seconds to minutes — never inside a lock)
_DEVICE_DISPATCH = {"predict_fn", "warmup"}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def receiver_name(node: ast.AST) -> Optional[str]:
    """The name a method/attr hangs off: ``rep.batcher.submit`` -> the
    receiver of ``submit`` is ``batcher``; ``self.fleet._cond`` -> the
    receiver of ``_cond`` is ``fleet``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


@dataclass
class CallSite:
    node: ast.Call
    held: Tuple[str, ...]
    line: int


@dataclass
class AttrWrite:
    owner: Optional[str]       # class key "mod::Class", None if unknown
    attr: str
    line: int
    held: Tuple[str, ...]
    is_self: bool


@dataclass
class FuncInfo:
    fid: str                   # "mod::Class.name" / "mod::name"
    module: str                # module rel path
    cls: Optional[str]         # class key or None
    name: str
    node: ast.AST
    is_init: bool = False
    acquires: List[Tuple[str, int, Tuple[str, ...]]] = field(
        default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    blocking: List[Tuple[str, int, Tuple[str, ...]]] = field(
        default_factory=list)
    attr_writes: List[AttrWrite] = field(default_factory=list)
    local_funcs: Dict[str, str] = field(default_factory=dict)
    # fixed-point summaries
    may_acquire: Set[str] = field(default_factory=set)
    may_block: Set[str] = field(default_factory=set)   # descriptions


@dataclass
class ClassInfo:
    key: str                   # "mod::Name"
    name: str
    module: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)     # last-part names
    # attr -> canonical attr (Condition(self._lock) aliases onto _lock)
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    lock_kinds: Dict[str, str] = field(default_factory=dict)
    thread_attrs: Set[str] = field(default_factory=set)
    event_attrs: Set[str] = field(default_factory=set)
    handle_attrs: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    self_attrs: Set[str] = field(default_factory=set)
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fid


class ModuleIndexData:
    def __init__(self, rel: str):
        self.rel = rel
        # local import bindings: name -> ("module", rel) | ("stdlib", top)
        self.imports: Dict[str, Tuple[str, str]] = {}
        self.module_locks: Dict[str, str] = {}     # name -> kind
        self.module_funcs: Dict[str, str] = {}     # name -> fid
        self.classes: Dict[str, ClassInfo] = {}    # class name -> info


class ProjectIndex:
    """See module docstring.  Built from an already-parsed Project."""

    def __init__(self, project):
        self.project = project
        self.mods: Dict[str, ModuleIndexData] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}          # key -> info
        self.class_by_name: Dict[str, List[ClassInfo]] = {}
        self.lock_owner: Dict[str, List[ClassInfo]] = {}  # attr -> classes
        self.attr_owner: Dict[str, List[ClassInfo]] = {}
        self.method_owner: Dict[str, List[ClassInfo]] = {}
        self.thread_names: Set[str] = set()
        self.event_names: Set[str] = set()
        self.socket_names: Set[str] = set()
        self.held_ctx: Set[str] = set()         # fids always under a lock
        self.callers: Dict[str, List[Tuple[str, bool]]] = {}
        for m in project.modules:
            self._scan_module(m)
        self._build_global_maps()
        for m in project.modules:
            self._analyze_module_functions(m)
        self._propagate()
        self._compute_held_contexts()

    # -- pass 1: declarations -------------------------------------------

    def _scan_module(self, m) -> None:
        data = ModuleIndexData(m.rel)
        self.mods[m.rel] = data
        mod_dir_parts = list(m.path.parent.relative_to(
            self.project.root).parts)
        for node in m.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    top = a.name.split(".")[0]
                    data.imports[a.asname or top] = ("stdlib", top)
            elif isinstance(node, ast.ImportFrom):
                self._bind_import_from(data, node, mod_dir_parts)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = self._lock_ctor_kind(node.value, data)
                if kind:
                    data.module_locks[node.targets[0].id] = kind
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fid = f"{m.rel}::{node.name}"
                data.module_funcs[node.name] = fid
                self.funcs[fid] = FuncInfo(fid, m.rel, None, node.name,
                                           node)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(data, m, node)

    def _bind_import_from(self, data: ModuleIndexData,
                          node: ast.ImportFrom, mod_dir: List[str]) -> None:
        if node.level == 0:
            top = (node.module or "").split(".")[0]
            for a in node.names:
                data.imports.setdefault(a.asname or a.name,
                                        ("stdlib", top))
            return
        base = mod_dir[: len(mod_dir) - (node.level - 1)]
        if node.module:
            base = base + node.module.split(".")
        root = self.project.root
        for a in node.names:
            cand = base + [a.name]
            p = root.joinpath(*cand)
            if p.with_suffix(".py").exists():
                data.imports[a.asname or a.name] = (
                    "module", p.with_suffix(".py").relative_to(
                        root).as_posix())
            elif (p / "__init__.py").exists():
                data.imports[a.asname or a.name] = (
                    "module", (p / "__init__.py").relative_to(
                        root).as_posix())
            else:
                bp = root.joinpath(*base)
                target = (bp.with_suffix(".py") if
                          bp.with_suffix(".py").exists()
                          else bp / "__init__.py")
                if target.exists():
                    data.imports[a.asname or a.name] = (
                        "symbol:" + a.name,
                        target.relative_to(root).as_posix())

    def _is_module_ref(self, data: ModuleIndexData, name: str,
                       stdlib: str) -> bool:
        binding = data.imports.get(name)
        return binding is not None and binding[0] == "stdlib" \
            and binding[1] == stdlib

    def _lock_ctor_kind(self, value: ast.AST,
                        data: ModuleIndexData) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        d = dotted(value.func)
        if d in ("threading.Lock", "threading.RLock",
                 "threading.Condition"):
            return d.split(".")[1]
        if d in ("Lock", "RLock", "Condition") \
                and data.imports.get(d) == ("stdlib", "threading"):
            return d
        return None

    def _scan_class(self, data: ModuleIndexData, m,
                    node: ast.ClassDef) -> None:
        key = f"{m.rel}::{node.name}"
        info = ClassInfo(key, node.name, m.rel, node,
                         bases=[b.split(".")[-1] for b in
                                (dotted(x) for x in node.bases) if b])
        data.classes[node.name] = info
        self.classes[key] = info
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            fid = f"{m.rel}::{node.name}.{item.name}"
            info.methods[item.name] = fid
            self.funcs[fid] = FuncInfo(
                fid, m.rel, key, item.name, item,
                is_init=item.name in ("__init__", "__new__",
                                      "__post_init__"))
            for sub in ast.walk(item):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        info.self_attrs.add(tgt.attr)
                        self._classify_ctor(info, tgt.attr, sub.value,
                                            data, sub.lineno)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    tgt = sub.target
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        info.self_attrs.add(tgt.attr)

    def _classify_ctor(self, info: ClassInfo, attr: str, value: ast.AST,
                       data: ModuleIndexData, lineno: int) -> None:
        kind = self._lock_ctor_kind(value, data)
        if kind:
            canonical = attr
            if kind == "Condition" and isinstance(value, ast.Call) \
                    and value.args:
                a0 = value.args[0]
                if isinstance(a0, ast.Attribute) and \
                        isinstance(a0.value, ast.Name) and \
                        a0.value.id == "self" and a0.attr in info.lock_attrs:
                    canonical = info.lock_attrs[a0.attr]
            info.lock_attrs[attr] = canonical
            info.lock_kinds[attr] = kind
            return
        if not isinstance(value, ast.Call):
            return
        d = dotted(value.func) or ""
        last = d.split(".")[-1]
        if d in ("threading.Thread",) or last == "Thread":
            info.thread_attrs.add(attr)
        elif d in ("threading.Event",) or last == "Event":
            info.event_attrs.add(attr)
        elif d in ("socket.socket",):
            info.handle_attrs[attr] = ("socket", lineno)
        elif last in ("ThreadingHTTPServer", "HTTPServer",
                      "TCPServer", "UDPServer"):
            info.handle_attrs[attr] = ("server", lineno)
        elif isinstance(value.func, ast.Name) and value.func.id == "open":
            info.handle_attrs[attr] = ("file", lineno)

    def _build_global_maps(self) -> None:
        for info in self.classes.values():
            self.class_by_name.setdefault(info.name, []).append(info)
            for attr in info.lock_attrs:
                self.lock_owner.setdefault(attr, []).append(info)
            for attr in info.self_attrs:
                self.attr_owner.setdefault(attr, []).append(info)
            for name in info.methods:
                self.method_owner.setdefault(name, []).append(info)
            self.thread_names |= info.thread_attrs
            self.event_names |= info.event_attrs
            self.socket_names |= {a for a, (k, _) in
                                  info.handle_attrs.items()
                                  if k == "socket"}

    # -- resolution ------------------------------------------------------

    def _class_for_receiver(self, recv: str,
                            candidates: Sequence[ClassInfo]
                            ) -> Optional[ClassInfo]:
        """Pick the class a receiver name plausibly denotes: exact,
        suffix, or prefix match on the lowered class name (``fleet`` ->
        ``Fleet``, ``batcher`` -> ``MicroBatcher``, ``rep`` ->
        ``Replica``).  Ambiguity -> None."""
        r = recv.lower().lstrip("_")
        if not r or r == "self":
            return None
        hits = []
        for c in candidates:
            cl = c.name.lower().lstrip("_")
            if cl == r or cl.endswith(r) or cl.startswith(r):
                hits.append(c)
        return hits[0] if len(hits) == 1 else None

    def lock_key(self, info: ClassInfo, attr: str) -> str:
        return f"{info.key}.{info.lock_attrs.get(attr, attr)}"

    def lock_kind(self, key: str) -> Optional[str]:
        mod_cls, _, attr = key.rpartition(".")
        info = self.classes.get(mod_cls)
        if info is not None:
            return info.lock_kinds.get(attr)
        rel, _, name = key.rpartition("::")
        data = self.mods.get(rel)
        return data.module_locks.get(name) if data else None

    def resolve_lock(self, expr: ast.AST, module: str,
                     cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            data = self.mods.get(module)
            if data and expr.id in data.module_locks:
                return f"{module}::{expr.id}"
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            info = self.classes.get(cls) if cls else None
            if info and attr in info.lock_attrs:
                return self.lock_key(info, attr)
            return None
        candidates = self.lock_owner.get(attr, [])
        if len(candidates) == 1:
            return self.lock_key(candidates[0], attr)
        r = receiver_name(recv)
        if r:
            hit = self._class_for_receiver(r, candidates)
            if hit is not None:
                return self.lock_key(hit, attr)
        return None

    def _method_in_hierarchy(self, info: ClassInfo,
                             name: str) -> Optional[str]:
        seen = set()
        stack = [info]
        while stack:
            c = stack.pop()
            if c.key in seen:
                continue
            seen.add(c.key)
            if name in c.methods:
                return c.methods[name]
            for b in c.bases:
                stack.extend(self.class_by_name.get(b, []))
        return None

    def resolve_call(self, call: ast.Call, module: str,
                     cls: Optional[str],
                     local_funcs: Dict[str, str]) -> Optional[str]:
        f = call.func
        data = self.mods.get(module)
        if isinstance(f, ast.Name):
            if f.id in local_funcs:
                return local_funcs[f.id]
            if data:
                if f.id in data.module_funcs:
                    return data.module_funcs[f.id]
                b = data.imports.get(f.id)
                if b and b[0].startswith("symbol:"):
                    target = self.mods.get(b[1])
                    if target:
                        return target.module_funcs.get(
                            b[0].split(":", 1)[1])
            return None
        if not isinstance(f, ast.Attribute):
            return None
        mname = f.attr
        recv = f.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls:
                info = self.classes.get(cls)
                if info:
                    hit = self._method_in_hierarchy(info, mname)
                    if hit:
                        return hit
                candidates = self.method_owner.get(mname, [])
                if len(candidates) == 1:
                    return candidates[0].methods[mname]
                return None
            if data:
                b = data.imports.get(recv.id)
                if b and b[0] == "module":
                    target = self.mods.get(b[1])
                    if target:
                        return target.module_funcs.get(mname)
        r = receiver_name(recv)
        candidates = self.method_owner.get(mname, [])
        if r:
            hit = self._class_for_receiver(r, candidates)
            if hit is not None:
                return hit.methods[mname]
        if len(candidates) == 1 and not mname.startswith("__"):
            return candidates[0].methods[mname]
        return None

    def resolve_attr_owner(self, target: ast.Attribute, module: str,
                           cls: Optional[str]
                           ) -> Tuple[Optional[str], bool]:
        """(owning class key, is_self) for an attribute STORE."""
        recv = target.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            return cls, True
        candidates = self.attr_owner.get(target.attr, [])
        if len(candidates) == 1:
            return candidates[0].key, False
        r = receiver_name(recv)
        if r:
            hit = self._class_for_receiver(r, candidates)
            if hit is not None:
                return hit.key, False
        return None, False

    # -- pass 2: per-function analysis -----------------------------------

    def _analyze_module_functions(self, m) -> None:
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._analyze_function(m.rel, None, node,
                                       f"{m.rel}::{node.name}")
            elif isinstance(node, ast.ClassDef):
                key = f"{m.rel}::{node.name}"
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._analyze_function(
                            m.rel, key, item,
                            f"{m.rel}::{node.name}.{item.name}")

    def _analyze_function(self, module: str, cls: Optional[str],
                          node, fid: str) -> None:
        fn = self.funcs.get(fid)
        if fn is None:
            fn = self.funcs[fid] = FuncInfo(fid, module, cls, node.name,
                                            node)
        self._walk_body(fn, node.body, ())

    def _walk_body(self, fn: FuncInfo, stmts, held: Tuple[str, ...]
                   ) -> None:
        for st in stmts:
            self._walk_stmt(fn, st, held)

    def _walk_stmt(self, fn: FuncInfo, st, held: Tuple[str, ...]) -> None:
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new = held
            for item in st.items:
                self._visit_expr(fn, item.context_expr, new)
                key = self.resolve_lock(item.context_expr, fn.module,
                                        fn.cls)
                if key:
                    fn.acquires.append((key, item.context_expr.lineno,
                                        new))
                    new = new + (key,)
            self._walk_body(fn, st.body, new)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body runs LATER, without the current locks
            # (worker loops, probe closures) — analyze it as its own
            # function with an empty held stack
            nested_fid = f"{fn.fid}.<locals>.{st.name}"
            fn.local_funcs[st.name] = nested_fid
            self.funcs[nested_fid] = FuncInfo(nested_fid, fn.module,
                                              fn.cls, st.name, st)
            self._analyze_function(fn.module, fn.cls, st, nested_fid)
            for dec in st.decorator_list:
                self._visit_expr(fn, dec, held)
        elif isinstance(st, ast.ClassDef):
            pass
        elif isinstance(st, ast.Try):
            self._walk_body(fn, st.body, held)
            for h in st.handlers:
                self._walk_body(fn, h.body, held)
            self._walk_body(fn, st.orelse, held)
            self._walk_body(fn, st.finalbody, held)
        elif isinstance(st, (ast.If, ast.While)):
            self._visit_expr(fn, st.test, held)
            self._walk_body(fn, st.body, held)
            self._walk_body(fn, st.orelse, held)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._visit_expr(fn, st.iter, held)
            self._record_writes(fn, [st.target], held)
            self._walk_body(fn, st.body, held)
            self._walk_body(fn, st.orelse, held)
        elif isinstance(st, ast.Assign):
            self._visit_expr(fn, st.value, held)
            self._record_writes(fn, st.targets, held)
        elif isinstance(st, ast.AugAssign):
            self._visit_expr(fn, st.value, held)
            self._record_writes(fn, [st.target], held)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._visit_expr(fn, st.value, held)
                self._record_writes(fn, [st.target], held)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._visit_expr(fn, child, held)
                elif isinstance(child, ast.stmt):
                    self._walk_stmt(fn, child, held)

    def _record_writes(self, fn: FuncInfo, targets, held) -> None:
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)
            elif isinstance(t, ast.Attribute):
                owner, is_self = self.resolve_attr_owner(t, fn.module,
                                                         fn.cls)
                fn.attr_writes.append(AttrWrite(owner, t.attr, t.lineno,
                                                held, is_self))

    def _visit_expr(self, fn: FuncInfo, expr, held: Tuple[str, ...]
                    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                continue  # body runs later; children still walked by
                # ast.walk, which is acceptable over-approximation for
                # CALL collection but lambdas rarely lock
            if isinstance(node, ast.Call):
                fn.calls.append(CallSite(node, held, node.lineno))
                desc = self._blocking_desc(fn, node, held)
                if desc:
                    fn.blocking.append((desc, node.lineno, held))
            elif isinstance(node, ast.NamedExpr) and \
                    isinstance(node.target, ast.Attribute):
                owner, is_self = self.resolve_attr_owner(
                    node.target, fn.module, fn.cls)
                fn.attr_writes.append(AttrWrite(
                    owner, node.target.attr, node.lineno, held, is_self))

    # -- blocking classification ----------------------------------------

    def _is_named_like(self, recv: ast.AST, known: Set[str],
                       hints: Tuple[str, ...]) -> bool:
        r = receiver_name(recv)
        if r is None:
            return False
        if r in known:
            return True
        rl = r.lower()
        return any(h in rl for h in hints)

    def _blocking_desc(self, fn: FuncInfo, call: ast.Call,
                       held: Tuple[str, ...]) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in _DEVICE_DISPATCH:
                return f"device dispatch {f.id}()"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        name = f.attr
        recv = f.value
        data = self.mods.get(fn.module)
        if name == "join" and self._is_named_like(
                recv, self.thread_names, ("thread", "worker", "proc")):
            return "thread join"
        if name in _SOCKET_METHODS and self._is_named_like(
                recv, self.socket_names, ("sock",)):
            return f"socket {name}()"
        if name in _SUBPROCESS_FUNCS and isinstance(recv, ast.Name) \
                and data and self._is_module_ref(data, recv.id,
                                                 "subprocess"):
            return f"subprocess.{name}()"
        if name == "sleep" and isinstance(recv, ast.Name) and data \
                and self._is_module_ref(data, recv.id, "time"):
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, (int, float)) \
                    and call.args[0].value < SLEEP_THRESHOLD_S:
                return None
            return "time.sleep()"
        if name == "wait":
            # Condition.wait on the lock you hold RELEASES it — never a
            # finding; Event.wait never releases anything
            lock = self.resolve_lock(recv, fn.module, fn.cls)
            if lock is not None:
                return None
            if self._is_named_like(recv, self.event_names, ()):
                return "Event.wait()"
            return None
        if name in _DEVICE_DISPATCH:
            return f"device dispatch .{name}()"
        return None

    # -- fixed points ----------------------------------------------------

    def _propagate(self) -> None:
        """may_acquire / may_block to a fixed point over resolved calls."""
        edges: Dict[str, Set[str]] = {}
        for fn in self.funcs.values():
            fn.may_acquire = {k for k, _, _ in fn.acquires}
            fn.may_block = {
                f"{d} ({fn.module}:{line})" for d, line, _ in fn.blocking}
            out = edges.setdefault(fn.fid, set())
            for site in fn.calls:
                callee = self.resolve_call(site.node, fn.module, fn.cls,
                                           fn.local_funcs)
                if callee and callee in self.funcs:
                    out.add(callee)
        changed = True
        guard = 0
        while changed and guard < 100:
            changed = False
            guard += 1
            for fn in self.funcs.values():
                for callee in edges.get(fn.fid, ()):
                    c = self.funcs[callee]
                    if not c.may_acquire <= fn.may_acquire:
                        fn.may_acquire |= c.may_acquire
                        changed = True
                    blk = {f"via {c.name}(): {d}" if not
                           d.startswith("via ") else d
                           for d in c.may_block}
                    if not blk <= fn.may_block:
                        fn.may_block |= blk
                        changed = True
        self.call_edges = edges

    def _compute_held_contexts(self) -> None:
        """fids whose EVERY resolved call site runs with a lock held (or
        from another held context) — ``_route`` is only ever called
        under the fleet condition, so its bare writes are lock-guarded
        in fact even though no ``with`` is lexically visible."""
        callers: Dict[str, List[Tuple[str, bool]]] = {}
        for fn in self.funcs.values():
            for site in fn.calls:
                callee = self.resolve_call(site.node, fn.module, fn.cls,
                                           fn.local_funcs)
                if callee and callee in self.funcs:
                    callers.setdefault(callee, []).append(
                        (fn.fid, bool(site.held)))
        self.callers = callers
        held = {fid for fid, fn in self.funcs.items()
                if fid in callers or fn.name.endswith("_locked")}
        changed = True
        guard = 0
        while changed and guard < 100:
            changed = False
            guard += 1
            for fid in list(held):
                fn = self.funcs[fid]
                if fn.name.endswith("_locked"):
                    continue
                ok = all(under or caller in held
                         for caller, under in callers.get(fid, ()))
                if not ok:
                    held.discard(fid)
                    changed = True
        self.held_ctx = held

    def is_held_context(self, fid: str) -> bool:
        return fid in self.held_ctx
