"""Rule families.  Importing this package registers every family in
:data:`tools.graftcheck.core.RULE_FAMILIES`:

========== ================== ==========================================
family     rules              guards
========== ================== ==========================================
locks      lock-order         one global lock order (deadlock freedom)
           lock-blocking      no joins/sockets/subprocess/sleep/device
                              dispatch while holding a lock
           lock-shared-attr   shared state locked everywhere or nowhere
tracer     jit-host-effect    no host side effects baked at trace time
jit        jit-raw            every jit in the compile ledger
           jit-closure        no function-identity cache defeats
ingress    ingress-assert     io/ invariants raise LightGBMError
           ingress-raw-parse  file tokens parse via io/guard helpers
lifecycle  thread-lifecycle   threads daemonized or joined
           handle-close       sockets/servers/files have a close path
           wall-clock         monotonic clocks on deadline math
phases     phase-taxonomy     host/device phase taxonomy in sync
params     param-docs         config params documented + rendered
metrics    metrics-docs       registry series names documented in
                              docs/OBSERVABILITY.md
resource   resource-raw-open  write-mode open() routes through
                              utils/diskguard.py (disk-full-safe sinks)
serve      serve-strategy-    strategy jits called only from the
           parity             _dispatch_binned/_dispatch_raw choke
                              points (fused/gather parity)
timing     timing-async-      no clock deltas around bare jit dispatch
           dispatch           (async dispatch measures enqueue, not
                              execution — sync or route via devprof)
========== ================== ==========================================
"""

from . import (ingress, jit, lifecycle, locks, metrics,  # noqa: F401
               params, phases, resource, serve, timing, tracer)
