"""Config/doc consistency rules (family ``params``).

``param-docs`` — every key in ``config.py _DEFAULTS`` must carry a
description in ``docs/_param_descriptions.py`` and render a row in
``docs/Parameters.md``; every description key must still exist in
``_DEFAULTS`` (aliases are documented on their canonical key).  PRs add
parameters faster than they add prose — this rule is what keeps
``docs/Parameters.md`` regen-complete instead of drifting one PR at a
time.

Everything is read statically (AST literals), so the rule never imports
the package or jax.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, family


def _dict_literal(tree: ast.AST, name: str
                  ) -> Optional[Tuple[Dict[str, int], int]]:
    """{key: lineno} of a module-level ``name = {...}`` dict literal,
    plus the assignment's line."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):   # _DEFAULTS: Dict[...] = {
            target = node.target
        else:
            continue
        if isinstance(target, ast.Name) and target.id == name \
                and isinstance(node.value, ast.Dict):
            keys = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value,
                                                              str):
                    keys[k.value] = k.lineno
            return keys, node.lineno
    return None


@family("params")
def check_params(project: Project) -> List[Finding]:
    cfg_path = project.pkg / "config.py"
    desc_path = project.root / "docs" / "_param_descriptions.py"
    md_path = project.root / "docs" / "Parameters.md"
    if not (cfg_path.exists() and desc_path.exists()):
        return []   # fixture trees without a config surface
    cfg_rel = cfg_path.relative_to(project.root).as_posix()
    desc_rel = desc_path.relative_to(project.root).as_posix()
    cfg_mod = project.module(cfg_rel)
    cfg_tree = cfg_mod.tree if cfg_mod else ast.parse(
        cfg_path.read_text())
    defaults = _dict_literal(cfg_tree, "_DEFAULTS")
    if defaults is None:
        return [Finding("param-docs", cfg_rel, 1,
                        "config.py no longer defines a _DEFAULTS dict "
                        "literal — the parameter docs can't be audited")]
    keys, _ = defaults
    desc = _dict_literal(ast.parse(desc_path.read_text()), "DESC")
    if desc is None:
        return [Finding("param-docs", desc_rel, 1,
                        "docs/_param_descriptions.py no longer defines a "
                        "DESC dict literal")]
    desc_keys, desc_line = desc
    findings: List[Finding] = []
    for key, lineno in sorted(keys.items()):
        if key not in desc_keys:
            findings.append(Finding(
                "param-docs", cfg_rel, lineno,
                f"param {key!r} has no description in "
                f"docs/_param_descriptions.py — docs/Parameters.md "
                f"renders an empty cell for it"))
    for key, lineno in sorted(desc_keys.items()):
        if key not in keys:
            findings.append(Finding(
                "param-docs", desc_rel, lineno,
                f"description for {key!r} matches no _DEFAULTS key — "
                f"stale, or an alias documented instead of its "
                f"canonical key"))
    if md_path.exists():
        md = md_path.read_text()
        for key, lineno in sorted(keys.items()):
            if f"`{key}`" not in md:
                findings.append(Finding(
                    "param-docs", cfg_rel, lineno,
                    f"param {key!r} is missing from docs/Parameters.md "
                    f"— regenerate with `python docs/gen_parameters.py`"))
    else:
        findings.append(Finding(
            "param-docs", cfg_rel, 1,
            "docs/Parameters.md does not exist — regenerate with "
            "`python docs/gen_parameters.py`"))
    return findings
