"""Phase-taxonomy rules (family ``phases``) — the former standalone
``tools/lint_phase_scopes.py``, migrated onto the shared graftcheck
walker so the whole suite costs one read+parse per file.  The standalone
entry point still works and delegates here; its ``check()`` contract
(a list of human-readable violation strings) is preserved verbatim for
``tests/test_phase_lint.py``.

Checks (unchanged from the standalone lint):

1. every ``timetag.scope("X")`` / ``obs.span`` / tracing-span literal
   under the package is declared in HOST_PHASES, and every declared host
   phase is used;
2. every ``jax.named_scope("X")`` in the jitted device files is declared
   in DEVICE_PHASES, and vice versa;
3. DEVICE_PARENT maps every device phase onto a declared host phase and
   covers every JITTED_HOST_PHASE;
4. every phase resolves through ``phases.span_series`` to a valid,
   UNIQUE Prometheus-safe histogram series name.
"""

from __future__ import annotations

import importlib.util
import pathlib
import re
from typing import Dict, List, Optional

from ..core import Finding, Project, family

SCOPE_RE = re.compile(
    r"(?:timetag\.scope|obs\.span|spans\.span"
    r"|obs\.trace_span|obs\.trace_begin|tracing\.span|TRACER\.(?:span|begin)"
    r")\(\s*[\"']([^\"']+)[\"']")
NAMED_RE = re.compile(r"jax\.named_scope\(\s*[\"']([^\"']+)[\"']")
SERIES_RE = re.compile(r"^phase_seconds_[a-z_][a-z0-9_]*$")

# the jitted paths carrying the device taxonomy: the growers plus the
# compiled-forest inference program (serve/forest.py)
DEVICE_FILES = ("ops/grow.py", "ops/ordered_grow.py", "serve/forest.py")


def _load_phases(pkg: pathlib.Path):
    spec = importlib.util.spec_from_file_location(
        "lightgbm_tpu_obs_phases", pkg / "obs" / "phases.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _scan_texts(texts: Dict[str, str], rx) -> Dict[str, List[str]]:
    found: Dict[str, List[str]] = {}
    for rel, text in texts.items():
        for m in rx.finditer(text):
            found.setdefault(m.group(1), []).append(rel)
    return found


def scope_errors(root, pkg, project: Optional[Project] = None
                 ) -> List[str]:
    """The standalone lint's ``check()``: violation strings, [] == clean.

    ``project`` (when given) supplies already-read file texts — the
    graftcheck run passes its shared Project so this family adds zero
    file reads; the standalone entry point omits it and one is built."""
    root = pathlib.Path(root)
    pkg = pathlib.Path(pkg)
    if project is None:
        project = Project(root, pkg_rel=str(pkg.relative_to(root)))
    phases = _load_phases(pkg)
    errors: List[str] = []

    # obs/ declares the taxonomy (docstrings mention the call forms); it
    # is not a scope *user*
    host_texts = {}
    device_texts = {}
    for m in project.modules:
        rel_to_pkg = pathlib.PurePosixPath(m.rel).relative_to(
            pathlib.PurePosixPath(project.pkg_rel))
        if "obs" not in rel_to_pkg.parts:
            host_texts[m.rel] = m.text
        if str(rel_to_pkg) in DEVICE_FILES:
            device_texts[m.rel] = m.text

    host_used = _scan_texts(host_texts, SCOPE_RE)
    for name, sites in sorted(host_used.items()):
        if name not in phases.HOST_PHASES:
            errors.append(
                f"timetag.scope({name!r}) in {sites} is not declared in "
                f"obs/phases.py HOST_PHASES")
    for name in sorted(phases.HOST_PHASES - set(host_used)):
        errors.append(
            f"HOST_PHASES declares {name!r} but no timetag.scope uses it")

    dev_used = _scan_texts(device_texts, NAMED_RE)
    for name, sites in sorted(dev_used.items()):
        if name not in phases.DEVICE_PHASES:
            errors.append(
                f"jax.named_scope({name!r}) in {sites} is not declared in "
                f"obs/phases.py DEVICE_PHASES")
    for name in sorted(phases.DEVICE_PHASES - set(dev_used)):
        errors.append(
            f"DEVICE_PHASES declares {name!r} but no jax.named_scope in "
            f"{DEVICE_FILES} uses it")

    for name in sorted(phases.DEVICE_PHASES):
        parent = phases.DEVICE_PARENT.get(name)
        if parent is None:
            errors.append(f"DEVICE_PARENT has no mapping for {name!r}")
        elif parent not in phases.HOST_PHASES:
            errors.append(
                f"DEVICE_PARENT maps {name!r} -> {parent!r}, which is not "
                f"a declared host phase")
    covered = set(phases.DEVICE_PARENT.values())
    for name in sorted(phases.JITTED_HOST_PHASES - covered):
        errors.append(
            f"jitted host phase {name!r} has no device phase mapped onto "
            f"it — traces inside it would be unattributable")

    # -- 4: phase taxonomy <-> metrics namespace (obs/spans.py) ---------
    span_series = getattr(phases, "span_series", None)
    if span_series is None:
        errors.append("obs/phases.py no longer defines span_series() — "
                      "the span/metrics namespace is unmapped")
        return errors
    seen: Dict[str, str] = {}
    for name in sorted(phases.HOST_PHASES | phases.DEVICE_PHASES):
        series = span_series(name)
        if not SERIES_RE.match(series):
            errors.append(
                f"span_series({name!r}) = {series!r} is not a valid "
                f"phase histogram series name ({SERIES_RE.pattern})")
        if series in seen:
            errors.append(
                f"phases {seen[series]!r} and {name!r} collide onto the "
                f"same span series {series!r}")
        seen[series] = name
    return errors


@family("phases")
def check_phases(project: Project) -> List[Finding]:
    anchor = f"{project.pkg_rel}/obs/phases.py"
    if not (project.pkg / "obs" / "phases.py").exists():
        return []   # fixture trees without a taxonomy have nothing to sync
    return [Finding("phase-taxonomy", anchor, 1, msg)
            for msg in scope_errors(project.root, project.pkg,
                                    project=project)]
