"""Data-ingress containment rules (family ``ingress``).

The data boundary (``lightgbm_tpu/io/``) is where external bytes become
training state; PR 13's containment layer (io/guard.py,
docs/FAULT_TOLERANCE.md §Data boundary) only holds if every invariant
failure is a *named* ``LightGBMError`` and every token conversion is
*classified*.  Two rules keep future PRs honest:

``ingress-assert`` — a bare ``assert`` anywhere under ``io/`` is a
finding.  Data-dependent invariants (row counts, offsets, widths) fail
on dirty FILES, not buggy code; an assert gives the operator a stack
trace instead of a file:line diagnostic, and vanishes entirely under
``python -O``.  Raise ``LightGBMError`` (or ``log.fatal``) instead.

``ingress-raw-parse`` — a raw ``float()``/``int()`` applied to a file
token (a value derived from ``.split()``/``.partition()``/
``.splitlines()``/``.readline()``/``.read()`` within the same function)
outside the ``io/guard.py`` helpers is a finding.  Raw conversions
throw bare ``ValueError`` with no file/line/token context and hard-code
their own NA semantics; ``guard.feature_value`` / ``guard.column_index``
are the single conversion point the quarantine policy hangs off.
``io/guard.py`` itself is exempt — it IS the helper layer.

The taint tracking is intraprocedural and syntactic (assignments,
tuple unpacks, for-targets, and comprehension targets seeded from the
string-splitting calls above, propagated through subscripts/attributes
of tainted names) — cheap, zero false positives on config-string
parsing in ``io/column_roles.py``, and exactly sharp enough to catch
the pattern that used to live in ``io/parser.py``.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Finding, Project, family

#: method calls whose results are file-token sources
_SPLIT_METHODS = {"split", "rsplit", "partition", "rpartition",
                  "splitlines", "readline", "readlines", "read"}

#: conversion builtins that must route through the guard helpers
_RAW_CONVERSIONS = {"float", "int"}

_GUARD_MODULE = "io/guard.py"


def _is_split_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _SPLIT_METHODS)


def _expr_has_split(node: ast.AST) -> bool:
    return any(_is_split_call(n) for n in ast.walk(node))


def _expr_taints(node: ast.AST, tainted: Set[str]) -> bool:
    """Does evaluating ``node`` touch a token source — a splitting call
    or an already-tainted name?"""
    for n in ast.walk(node):
        if _is_split_call(n):
            return True
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


def _target_names(target: ast.AST) -> List[str]:
    names: List[str] = []
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            names.append(n.id)
    return names


def _function_findings(fn: ast.AST, rel: str) -> List[Finding]:
    """Two fixpoint-ish passes: collect tainted names, then flag raw
    conversions whose arguments reference them.  Nested functions are
    walked as part of their parent (their names share the closure)."""
    tainted: Set[str] = set()
    for _ in range(2):      # second pass catches forward references
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if _expr_taints(node.value, tainted):
                    for t in node.targets:
                        tainted.update(_target_names(t))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if _expr_taints(node.value, tainted):
                    tainted.update(_target_names(node.target))
            elif isinstance(node, ast.For):
                if _expr_taints(node.iter, tainted):
                    tainted.update(_target_names(node.target))
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                for gen in node.generators:
                    if _expr_taints(gen.iter, tainted):
                        tainted.update(_target_names(gen.target))
    findings: List[Finding] = []
    if not tainted:
        return findings
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _RAW_CONVERSIONS):
            continue
        if any(_expr_taints(arg, tainted) for arg in node.args):
            findings.append(Finding(
                "ingress-raw-parse", rel, node.lineno,
                f"raw {node.func.id}() on a file token — route it "
                f"through io/guard.py (feature_value/column_index) so "
                f"malformed tokens are classified and quarantinable "
                f"instead of raising a bare ValueError"))
    return findings


@family("ingress")
def check_ingress(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    io_prefix = f"{project.pkg_rel}/io/"
    for mod in project.modules:
        if not mod.rel.startswith(io_prefix):
            continue
        # -- ingress-assert: io/ invariants must be named errors -------
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assert):
                findings.append(Finding(
                    "ingress-assert", mod.rel, node.lineno,
                    "bare assert at the data boundary — a data-"
                    "dependent invariant must raise LightGBMError "
                    "(named file/line diagnostics, survives python -O)"))
        # -- ingress-raw-parse: conversions through the guard only -----
        if mod.rel.endswith(_GUARD_MODULE):
            continue            # the helper layer itself
        # module-level statements count as one scope; functions each
        # get their own taint universe
        funcs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        # skip nested functions (already walked via their parent)
        tops: List[ast.AST] = []
        nested: Set[int] = set()
        for f in funcs:
            for inner in ast.walk(f):
                if inner is not f and isinstance(
                        inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.add(id(inner))
        tops = [f for f in funcs if id(f) not in nested]
        for f in tops:
            findings.extend(_function_findings(f, mod.rel))
    return findings
