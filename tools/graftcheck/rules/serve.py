"""Serving-strategy discipline rules (family ``serve``).

- ``serve-strategy-parity`` — a compiled-forest jit invoked directly
  (``self._binned_jit(...)``, ``self._raw_jit(...)``,
  ``self._walk_binned_jit(...)``, ``self._walk_raw_jit(...)``) anywhere
  in ``lightgbm_tpu/serve/`` outside the two strategy dispatchers
  (``CompiledForest._dispatch_binned`` / ``_dispatch_raw``).  The
  fused-walk strategy (PR 20) only keeps its guarantees — gather stays
  byte-identical in programs and output, fused/gather stay swappable
  per forest — if strategy selection happens in exactly one place per
  input kind.  A call site that picks a jit itself silently hardwires
  one strategy, skips the quantized-input remap, and bypasses the
  fallback semantics; route it through the dispatcher instead (or waive
  with an inline suppression so the bypass stays visible and counted).
  Constructing the CountingJits (``self._binned_jit = CountingJit(...)``)
  is fine everywhere — only *calls* are strategy decisions.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Project, family

# the per-strategy CountingJit attributes of serve/forest.py; calling
# one directly IS a strategy decision, so it belongs in a dispatcher
_STRATEGY_JITS = {"_binned_jit", "_raw_jit",
                  "_walk_binned_jit", "_walk_raw_jit"}

# the only functions allowed to pick a strategy jit
_DISPATCHERS = {"_dispatch_binned", "_dispatch_raw"}


@family("serve")
def check_serve(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        if "/serve/" not in f"/{m.rel}":
            continue

        def visit(node, func_name: str):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STRATEGY_JITS
                    and func_name not in _DISPATCHERS):
                findings.append(Finding(
                    "serve-strategy-parity", m.rel, node.lineno,
                    f"direct {node.func.attr}(...) call outside the "
                    f"strategy dispatchers — route through "
                    f"_dispatch_binned/_dispatch_raw so serve_walk "
                    f"selection, quantized-input remap and fallback "
                    f"semantics stay in one place per input kind"))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_name = node.name
            for child in ast.iter_child_nodes(node):
                visit(child, func_name)

        visit(m.tree, "")
    return findings
