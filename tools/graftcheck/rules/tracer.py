"""Jit tracer-safety rules (family ``tracer``).

``jit-host-effect`` — a host side effect inside a function handed to a
repo jit entry point (``@jax.jit`` / ``@instrumented_jit`` decorators,
``jax.jit(f)`` / ``instrumented_jit(f)`` / ``CountingJit(f, ...)`` /
``shard_map(f, ...)`` call forms).  Traced Python runs ONCE, at trace
time: a registry counter bumps once and never again, ``time.*`` bakes
the trace-time clock into the program as a constant, ``np.random``
freezes one draw forever, ``.item()``/host casts force a device sync
inside what should be an async dispatch, and ``nonlocal``/``global``
mutation of closed-over state happens at trace time, not per call.
Nothing crashes — the program silently computes something other than
what the author meant, which is why this needs a static gate rather
than a test.

The scan is lexical (the jitted body plus its nested defs); helper
calls out of the traced function are not followed — jitted helpers are
themselves scanned at their own definition.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, Project, family
from ..index import dotted

# call forms whose function argument is traced
_TRACING_CALLS = {"shard_map", "CountingJit", "instrumented_jit",
                  "pallas_call", "vmap", "pmap", "scan", "while_loop",
                  "fori_loop", "cond", "checkpoint", "remat", "grad",
                  "value_and_grad"}

# registry / gauge write surface (obs/registry.py and its re-exports)
_REGISTRY_CALLS = {"inc", "set_gauge", "observe"}


def _decorated_jit(node) -> bool:
    for dec in node.decorator_list:
        d = dotted(dec) or ""
        if isinstance(dec, ast.Call):
            d = dotted(dec.func) or ""
            if d in ("functools.partial", "partial") and dec.args:
                d = dotted(dec.args[0]) or ""
        if d in ("jax.jit", "instrumented_jit", "obs.instrumented_jit") \
                or d.endswith(".instrumented_jit"):
            return True
    return False


def _collect_jitted(tree: ast.AST) -> List[ast.AST]:
    """Function defs that are traced: jit-decorated, or passed (as the
    first argument, or by name) into a tracing call form."""
    jitted: List[ast.AST] = []
    defs_by_name = {}
    referenced: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
            if _decorated_jit(node):
                jitted.append(node)
        elif isinstance(node, ast.Call):
            name = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else "")
            d = dotted(node.func) or ""
            if name in _TRACING_CALLS or d == "jax.jit":
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        referenced.add(a.id)
    for name in referenced:
        node = defs_by_name.get(name)
        if node is not None and node not in jitted:
            jitted.append(node)
    return jitted


def _effect(node: ast.AST) -> Optional[str]:
    if isinstance(node, (ast.Nonlocal, ast.Global)):
        kw = "nonlocal" if isinstance(node, ast.Nonlocal) else "global"
        return (f"`{kw}` mutation of closed-over state runs at trace "
                f"time, once — not per call")
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        if f.id == "print":
            return "print() runs at trace time only"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    d = dotted(f) or ""
    root = d.split(".")[0]
    if root == "time":
        return (f"{d}() bakes the trace-time clock into the program as "
                f"a constant")
    if d.startswith(("np.random", "numpy.random", "random.")):
        return f"{d}() freezes one host RNG draw into the program"
    if f.attr == "item":
        return (".item() forces a host sync / concretization inside a "
                "traced function")
    if root in ("obs", "registry", "REGISTRY") \
            and f.attr in _REGISTRY_CALLS:
        return (f"{d}() is a host-side registry write; under trace it "
                f"fires once at compile time and never again")
    if isinstance(f.value, ast.Name) and f.value.id == "self" \
            and f.attr == "_inc":
        return ("self._inc() is a registry write; under trace it fires "
                "once at compile time and never again")
    return None


@family("tracer")
def check_tracer(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        for fn in _collect_jitted(m.tree):
            for node in ast.walk(fn):
                msg = _effect(node)
                if msg:
                    findings.append(Finding(
                        "jit-host-effect", m.rel, node.lineno,
                        f"in jitted `{fn.name}`: {msg}"))
    return findings
