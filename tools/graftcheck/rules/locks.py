"""Lock-discipline rules (family ``locks``).

- ``lock-order`` — two locks acquired in both orders anywhere in the
  repo (lexically nested ``with`` blocks, plus lock acquisitions
  reached through resolved calls made while a lock is held).  A
  consistent global order is the only deadlock-freedom argument a
  watchdog-less reader can check; one inversion is one interleaving
  away from a frozen fleet.  Re-acquiring a non-reentrant lock you
  already hold is reported under the same rule (self-deadlock).
- ``lock-blocking`` — a blocking operation (thread join, socket I/O,
  subprocess, ``time.sleep`` >= 10ms, ``Event.wait``, device dispatch
  like ``predict_fn``/``warmup``) executed while holding a lock, either
  directly or through a resolved call chain.  ``Condition.wait`` on the
  held lock releases it and is never flagged.
- ``lock-shared-attr`` — an attribute written under a lock at one site
  but written bare at another: either the lock is load-bearing (the
  bare site races) or it is theater (and the next reader will copy the
  wrong pattern).  Constructors are exempt (happens-before publication),
  as are functions whose every resolved call site runs under a lock and
  helpers named ``*_locked``.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..core import Finding, Project, family

_INIT_NAMES = {"__init__", "__new__", "__post_init__", "__enter__"}


def _short(key: str) -> str:
    """mod::Class.attr -> Class.attr / mod::name -> mod:name for
    messages."""
    rel, _, rest = key.partition("::")
    return rest if "." in rest else f"{rel.rsplit('/', 1)[-1]}:{rest}"


@family("locks")
def check_locks(project: Project) -> List[Finding]:
    idx = project.index
    findings: List[Finding] = []

    # -- lock-order ------------------------------------------------------
    # A bare threading.Condition() is backed by an RLock and reentrant;
    # only plain Locks (including Conditions aliased onto one via
    # Condition(self._lock)) self-deadlock on re-acquisition.
    def _reentrant(key: str) -> bool:
        return idx.lock_kind(key) != "Lock"

    # edge (held, acquired) -> first example (module, line, via)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for fn in idx.funcs.values():
        for key, line, held in fn.acquires:
            for h in held:
                if h != key:
                    edges.setdefault((h, key), (fn.module, line, ""))
                elif not _reentrant(key):
                    findings.append(Finding(
                        "lock-order", fn.module, line,
                        f"{_short(key)} re-acquired while already held "
                        f"— self-deadlock for a non-reentrant lock"))
        for site in fn.calls:
            if not site.held:
                continue
            callee = idx.resolve_call(site.node, fn.module, fn.cls,
                                      fn.local_funcs)
            if callee is None or callee not in idx.funcs:
                continue
            cfn = idx.funcs[callee]
            for a in cfn.may_acquire:
                for h in site.held:
                    if h != a:
                        edges.setdefault(
                            (h, a), (fn.module, site.line,
                                     f" via {cfn.name}()"))
                    elif not _reentrant(a):
                        findings.append(Finding(
                            "lock-order", fn.module, site.line,
                            f"call to {cfn.name}() re-acquires "
                            f"{_short(a)} already held here — "
                            f"self-deadlock for a non-reentrant lock"))
    for (a, b), (mod, line, via) in sorted(edges.items()):
        if (b, a) in edges and a < b:
            mod2, line2, via2 = edges[(b, a)]
            findings.append(Finding(
                "lock-order", mod, line,
                f"lock-order inversion: {_short(a)} -> {_short(b)} "
                f"here{via}, but {_short(b)} -> {_short(a)} at "
                f"{mod2}:{line2}{via2} — two threads taking the pair in "
                f"opposite orders deadlock"))

    # -- lock-blocking ---------------------------------------------------
    for fn in idx.funcs.values():
        for desc, line, held in fn.blocking:
            if held:
                findings.append(Finding(
                    "lock-blocking", fn.module, line,
                    f"{desc} while holding {_short(held[-1])} — every "
                    f"other thread contending the lock stalls for the "
                    f"full duration"))
        for site in fn.calls:
            if not site.held:
                continue
            callee = idx.resolve_call(site.node, fn.module, fn.cls,
                                      fn.local_funcs)
            if callee is None or callee not in idx.funcs:
                continue
            blk = idx.funcs[callee].may_block
            if blk:
                sample = sorted(blk)[0]
                findings.append(Finding(
                    "lock-blocking", fn.module, site.line,
                    f"call to {idx.funcs[callee].name}() may block "
                    f"({sample}) while holding {_short(site.held[-1])}"))

    # -- lock-shared-attr ------------------------------------------------
    # (class key, attr) -> {"held": [(mod,line)], "bare": [(mod,line)]}
    writes: Dict[Tuple[str, str], Dict[str, List[Tuple[str, int]]]] = {}
    for fn in idx.funcs.values():
        if fn.is_init or fn.name in _INIT_NAMES:
            continue
        guarded_fn = idx.is_held_context(fn.fid)
        for w in fn.attr_writes:
            owner = w.owner
            if owner is None:
                continue
            slot = writes.setdefault((owner, w.attr),
                                     {"held": [], "bare": []})
            if w.held or guarded_fn:
                slot["held"].append((fn.module, w.line))
            else:
                slot["bare"].append((fn.module, w.line))
    for (owner, attr), slot in sorted(writes.items()):
        if not slot["held"] or not slot["bare"]:
            continue
        hmod, hline = slot["held"][0]
        cls_name = owner.rpartition("::")[2]
        for bmod, bline in slot["bare"]:
            findings.append(Finding(
                "lock-shared-attr", bmod, bline,
                f"{cls_name}.{attr} written here with no lock, but "
                f"written under a lock at {hmod}:{hline} — either this "
                f"site races or the lock there is theater"))
    return findings
