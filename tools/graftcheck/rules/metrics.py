"""Registry/doc consistency rules (family ``metrics``).

``metrics-docs`` — every registry series name a package module writes
(an ``inc`` / ``set_gauge`` / ``observe`` / ``labeled_name`` call whose
first argument is a string literal) must appear in
``docs/OBSERVABILITY.md``.  Series names are the observability API:
dashboards, the lifecycle gates, and ``obs-report`` all key on them, and
a name that exists only in code is a metric nobody can discover.  PRs
add series faster than they add prose — this rule is what keeps the
metrics reference complete instead of drifting one PR at a time.

Names built by concatenation (``"devprof_samples_" + prog``) are not
literals and audit at whatever literal site publishes their family
instead; fully dynamic names need an inline suppression.  Everything is
read statically (AST), so the rule never imports the package or jax.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Project, family

# the registry's writer surface (obs/registry.py + obs/prom.py), plus
# the aliased forms modules import them under
_WRITERS = {"inc", "set_gauge", "observe", "labeled_name",
            "_inc", "_set_gauge", "_observe"}


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


@family("metrics")
def check_metrics_docs(project: Project) -> List[Finding]:
    docs_path = project.root / "docs" / "OBSERVABILITY.md"
    if not docs_path.exists():
        return []   # fixture trees without the audited docs file
    docs = docs_path.read_text()
    findings: List[Finding] = []
    seen = set()
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node.func) not in _WRITERS or not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            name = arg.value
            if name in docs or (mod.rel, name) in seen:
                continue
            seen.add((mod.rel, name))
            findings.append(Finding(
                "metrics-docs", mod.rel, node.lineno,
                f"registry series {name!r} is not documented in "
                f"docs/OBSERVABILITY.md — every published series must "
                f"be discoverable there"))
    return findings
