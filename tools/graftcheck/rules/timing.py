"""Async-dispatch timing rule (family ``timing``).

- ``timing-async-dispatch`` — a ``time.perf_counter()`` /
  ``time.monotonic()`` delta window that contains a call to a
  known-jitted callable with no synchronization in between.  JAX
  dispatch is asynchronous: the wall clock around a bare jit call
  measures *enqueue* time, not device execution, so the resulting
  "timing" silently reports microseconds for milliseconds of work.
  The window must contain a sync marker — ``block_until_ready`` /
  ``device_get`` / ``.item()`` / ``np.asarray`` / anything routed
  through ``obs.devprof`` (whose ``sync``/``timed_dispatch`` helpers
  exist precisely so timed code has one audited sync path).

Known-jitted callables are resolved per module: names bound at module
level from ``jax.jit(...)`` / ``instrumented_jit(...)`` (including the
``obs.instrumented_jit`` spelling), and functions decorated with
either.  Calls through attributes (``self._fn(...)``) are out of scope
— the in-package dispatch seam (``obs/compile_ledger.py``) owns those
and already syncs via devprof.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Project, family
from ..index import dotted

# module-clock expressions that start/stop a timing window
_TIMER_CALLS = {"time.perf_counter", "time.monotonic",
                "perf_counter", "monotonic"}

# jit-producing callables (last dotted segment)
_JIT_MAKERS = {"jit", "instrumented_jit", "InstrumentedJit"}

# attribute calls that force device completion inside a window
_SYNC_ATTRS = {"block_until_ready", "device_get", "item", "sync",
               "asarray", "timed_dispatch"}
_SYNC_DOTTED_PREFIXES = ("devprof.", "obs.devprof.")


def _is_timer_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (dotted(node.func) or "") in _TIMER_CALLS)


def _jit_names(tree: ast.Module) -> Set[str]:
    """Module-level names that are jitted callables."""
    names: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            d = dotted(node.value.func) or ""
            if d.split(".")[-1] in _JIT_MAKERS:
                names.add(node.targets[0].id)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = dotted(target) or ""
            if d.split(".")[-1] in _JIT_MAKERS:
                names.add(node.name)
    return names


def _is_sync(node: ast.Call) -> bool:
    d = dotted(node.func) or ""
    if d.startswith(_SYNC_DOTTED_PREFIXES):
        return True
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _SYNC_ATTRS:
        return True
    return False


def _scan_function(m, fn: ast.AST, jits: Set[str],
                   findings: List[Finding]) -> None:
    starts: Dict[str, int] = {}
    deltas: List[Tuple[str, int]] = []
    jit_calls: List[Tuple[str, int]] = []
    syncs: List[int] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_timer_call(node.value):
            starts[node.targets[0].id] = node.lineno
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and isinstance(node.right, ast.Name):
            deltas.append((node.right.id, node.lineno))
        elif isinstance(node, ast.Call):
            if _is_sync(node):
                syncs.append(node.lineno)
            elif isinstance(node.func, ast.Name) and node.func.id in jits:
                jit_calls.append((node.func.id, node.lineno))
    for var, end in deltas:
        start = starts.get(var)
        if start is None or end <= start:
            continue
        hit = next((j for j in jit_calls if start < j[1] <= end), None)
        if hit is None:
            continue
        if any(start < s <= end for s in syncs):
            continue
        findings.append(Finding(
            "timing-async-dispatch", m.rel, end,
            f"clock delta over `{var}` spans a call to jitted "
            f"`{hit[0]}` (line {hit[1]}) with no sync — JAX dispatch "
            f"is async, so this measures enqueue time, not execution; "
            f"block_until_ready the result or route through "
            f"obs.devprof"))


@family("timing")
def check_timing(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        jits = _jit_names(m.tree)
        if not jits:
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_function(m, node, jits, findings)
    return findings
