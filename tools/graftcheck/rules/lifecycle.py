"""Thread/handle/clock lifecycle rules (family ``lifecycle``).

- ``thread-lifecycle`` — every ``threading.Thread`` must be daemonized
  (``daemon=True`` at construction) or joined somewhere in its module.
  A non-daemon, never-joined thread keeps the interpreter alive after
  ``main`` returns — the CLI "hang at exit" class, invisible in tests
  that never exit the process.
- ``handle-close`` — a socket / HTTP server / file handle stored on
  ``self`` must have a close path in its class (``close`` /
  ``server_close`` / ``shutdown`` on the same attribute); a local
  ``open()`` outside a ``with`` must be ``close()``d in the same
  function.  The serve/watchdog layers restart components (hot reload,
  probe re-admission) — a leaked fd per cycle is a crash with a delay.
- ``wall-clock`` — ``time.time()`` feeding arithmetic or comparison.
  Deadline and staleness math must use the monotonic clock: the fleet
  request deadline and the watchdog's heartbeat ages both die on
  NTP/wall-clock steps.  Pure timestamping (``{"t": round(time.time(),
  3)}``) is not flagged — epoch time is the right value to RECORD, and
  the wrong value to SUBTRACT.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, Project, family
from ..index import dotted, receiver_name

_CLOSE_METHODS = {"close", "server_close", "shutdown", "stop"}


def _has_kw_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _assigned_name(parents: Dict[ast.AST, ast.AST],
                   call: ast.Call) -> Optional[str]:
    """The attr/var a constructor result lands in, if any."""
    p = parents.get(call)
    if isinstance(p, ast.Assign) and len(p.targets) == 1:
        t = p.targets[0]
        if isinstance(t, ast.Attribute):
            return t.attr
        if isinstance(t, ast.Name):
            return t.id
    return None


def _build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


@family("lifecycle")
def check_lifecycle(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    findings += _check_threads(project)
    findings += _check_handles(project)
    findings += _check_wall_clock(project)
    return findings


# -- thread-lifecycle ----------------------------------------------------

def _check_threads(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        parents = _build_parents(m.tree)
        joined: Set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                r = receiver_name(node.func.value)
                if r:
                    joined.add(r)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            if d != "threading.Thread" and d.split(".")[-1] != "Thread":
                continue
            if d.split(".")[-1] == "Thread" and d != "threading.Thread" \
                    and d != "Thread":
                continue
            if _has_kw_true(node, "daemon"):
                continue
            target = _assigned_name(parents, node)
            if target is not None and target in joined:
                continue
            findings.append(Finding(
                "thread-lifecycle", m.rel, node.lineno,
                "Thread is neither daemon=True nor joined in this "
                "module — a live non-daemon thread blocks interpreter "
                "exit (and a crashed owner leaks it silently)"))
    return findings


# -- handle-close --------------------------------------------------------

_HANDLE_KINDS = {"socket": "socket", "server": "HTTP server",
                 "file": "file handle"}


def _check_handles(project: Project) -> List[Finding]:
    idx = project.index
    findings: List[Finding] = []
    for info in idx.classes.values():
        if not info.handle_attrs:
            continue
        mod = project.module(info.module)
        if mod is None:
            continue
        closed: Set[str] = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _CLOSE_METHODS:
                r = receiver_name(node.func.value)
                if r:
                    closed.add(r)
        for attr, (kind, lineno) in sorted(info.handle_attrs.items()):
            if attr not in closed:
                findings.append(Finding(
                    "handle-close", info.module, lineno,
                    f"{info.name}.{attr} holds a {_HANDLE_KINDS[kind]} "
                    f"with no close path in the class — restart/reload "
                    f"cycles leak one per generation"))
    # local open() outside `with`, never closed in the same function
    for m in project.modules:
        parents = _build_parents(m.tree)
        for node in ast.walk(m.tree):
            if not (isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))):
                continue
            closed = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _CLOSE_METHODS:
                    r = receiver_name(sub.func.value)
                    if r:
                        closed.add(r)
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "open"):
                    continue
                p = parents.get(sub)
                if isinstance(p, ast.withitem):
                    continue
                if isinstance(p, ast.Assign) and len(p.targets) == 1 \
                        and isinstance(p.targets[0], ast.Attribute):
                    continue   # self.X handles: the class-level check
                name = _assigned_name(parents, sub)
                if name is None or name in closed:
                    continue
                findings.append(Finding(
                    "handle-close", m.rel, sub.lineno,
                    f"open() result `{name}` has no close path in "
                    f"`{node.name}` — use `with open(...)` or close it "
                    f"on every exit path"))
    return findings


# -- wall-clock ----------------------------------------------------------

def _check_wall_clock(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        parents = _build_parents(m.tree)
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call)
                    and dotted(node.func) == "time.time"):
                continue
            if _feeds_math(node, parents):
                findings.append(Finding(
                    "wall-clock", m.rel, node.lineno,
                    "time.time() feeds arithmetic/comparison — deadline "
                    "and elapsed math must use time.monotonic() (or "
                    "perf_counter); the wall clock steps under NTP and "
                    "this computation steps with it"))
    return findings


def _feeds_math(call: ast.Call, parents: Dict[ast.AST, ast.AST]) -> bool:
    p = parents.get(call)
    if isinstance(p, (ast.BinOp, ast.Compare, ast.AugAssign, ast.UnaryOp)):
        return True
    # assigned to a name later used in arithmetic within the function
    if isinstance(p, ast.Assign) and len(p.targets) == 1 \
            and isinstance(p.targets[0], ast.Name):
        name = p.targets[0].id
        fn = p
        while fn is not None and not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            fn = parents.get(fn)
        if fn is None:
            return False
        for node in ast.walk(fn):
            if isinstance(node, (ast.BinOp, ast.Compare)):
                for leaf in ast.walk(node):
                    if isinstance(leaf, ast.Name) and leaf.id == name:
                        return True
    return False
