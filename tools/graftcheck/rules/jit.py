"""Recompile/program-discipline rules (family ``jit``).

- ``jit-raw`` — a raw ``jax.jit`` call/decorator outside
  ``obs/compile_ledger.py`` (the one sanctioned wrapper).  Every repo
  jit must route through ``obs.instrumented_jit`` / ``CountingJit`` so
  its compiles land in the compile ledger; raw sites are exactly the
  blind spots BENCH_r02-r05 could not attribute (34-321s of warmup with
  no program names).  A site whose jit is wrapped by a CountingJit one
  level up is still flagged — waive it with an inline suppression so
  the indirection is visible and counted.
- ``jit-closure`` — ``jax.jit``/``instrumented_jit`` applied to a
  ``lambda``, or invoked inside a loop.  jax caches compiled programs
  by FUNCTION IDENTITY; a fresh closure per call site defeats the cache
  and recompiles every time (the exact bug class PR 9's
  ``fresh_train_programs`` fixture had to work around — see
  ``models/gbdt.py _SHARED_JITS``).
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Project, family

# the one module allowed to say jax.jit: the instrumented wrapper itself
_SANCTIONED = ("obs/compile_ledger.py",)

_JIT_WRAPPERS = {"jit", "instrumented_jit"}


def _is_jax_jit(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax")


def _wrapper_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@family("jit")
def check_jit(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        if any(m.rel.endswith(s) for s in _SANCTIONED):
            continue
        # parent + loop-depth tracking in one walk
        loop_stack: List[ast.AST] = []

        def visit(node, in_loop: bool):
            if _is_jax_jit(node):
                findings.append(Finding(
                    "jit-raw", m.rel, node.lineno,
                    "raw jax.jit — route through obs.instrumented_jit "
                    "(or CountingJit) so the compile ledger records this "
                    "program's compiles, shapes and seconds"))
            if isinstance(node, ast.Call):
                name = _wrapper_name(node.func)
                if name in _JIT_WRAPPERS or _is_jax_jit(node.func):
                    if any(isinstance(a, ast.Lambda) for a in node.args):
                        findings.append(Finding(
                            "jit-closure", m.rel, node.lineno,
                            f"{name or 'jax.jit'}(lambda ...) — jax "
                            f"caches programs by function identity; a "
                            f"fresh lambda per call recompiles every "
                            f"time (cache the jitted callable instead, "
                            f"like models/gbdt.py _SHARED_JITS)"))
                    elif in_loop:
                        findings.append(Finding(
                            "jit-closure", m.rel, node.lineno,
                            f"{name or 'jax.jit'}(...) inside a loop — "
                            f"every iteration builds a new traced "
                            f"callable, defeating jax's "
                            f"function-identity program cache"))
            entering_loop = isinstance(node, (ast.For, ast.While,
                                              ast.AsyncFor))
            for child in ast.iter_child_nodes(node):
                visit(child, in_loop or entering_loop)

        visit(m.tree, False)
    return findings
