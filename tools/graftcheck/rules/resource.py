"""Resource-exhaustion containment rules (family ``resource``).

PR 15's diskguard layer (``lightgbm_tpu/utils/diskguard.py``,
docs/FAULT_TOLERANCE.md §Resource exhaustion) only holds if every write
path actually routes through it: one forgotten bare ``open(..., "w")``
in a future telemetry sink re-creates the failure class the layer
removed — a full disk crashing a training run from inside an observer.

``resource-raw-open`` — a write-capable ``open()`` (mode containing
``w``/``a``/``x``/``+``) anywhere in the package outside the funnel
modules is a finding.  Exempt:

- ``utils/diskguard.py`` — it IS the funnel;
- ``snapshot.py`` — owns the atomic tmp+fsync+replace protocol and
  routes its data writes through ``diskguard.write_file_atomic``
  already (its read-modify helpers hold the exemption);
- everything under ``testing/`` — the fault injectors corrupt files on
  purpose, with raw opens, which is their job.

Telemetry/state sinks must use ``diskguard.GuardedWriter`` /
``append_line`` / ``write_file_atomic`` (classified failures degrade);
artifact writes (model files, binary datasets, prediction output) must
use ``diskguard.artifact_write`` (classified failures are NAMED
fatals).  Like every family, suppressions (``# graftcheck:
disable=resource-raw-open``) are visible and counted, never silent.

The check is purely syntactic (an ``ast`` walk for ``open`` calls with
a constant write mode) — a non-constant mode expression is not judged,
matching the suite's zero-false-positive bias.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Project, family

#: modules allowed to call write-mode open() directly
_EXEMPT_FILES = ("utils/diskguard.py", "snapshot.py")
_EXEMPT_DIRS = ("testing/",)

_WRITE_CHARS = set("wax+")


def _open_write_mode(node: ast.Call) -> Optional[str]:
    """The constant mode string of an ``open()`` call when it is
    write-capable, else None (read mode, or a mode the walk cannot
    evaluate)."""
    if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
        return None
    mode_node: Optional[ast.AST] = None
    if len(node.args) >= 2:
        mode_node = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if not (isinstance(mode_node, ast.Constant)
            and isinstance(mode_node.value, str)):
        return None
    mode = mode_node.value
    return mode if (_WRITE_CHARS & set(mode)) else None


@family("resource")
def check_resource(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    pkg_prefix = f"{project.pkg_rel}/"
    for mod in project.modules:
        rel_in_pkg = mod.rel[len(pkg_prefix):] \
            if mod.rel.startswith(pkg_prefix) else mod.rel
        # exact relative paths, not endswith: a future
        # serve/state_snapshot.py must NOT inherit snapshot.py's waiver
        if rel_in_pkg in _EXEMPT_FILES:
            continue
        if any(rel_in_pkg.startswith(d) for d in _EXEMPT_DIRS):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            mode = _open_write_mode(node)
            if mode is None:
                continue
            findings.append(Finding(
                "resource-raw-open", mod.rel, node.lineno,
                f"bare open(..., {mode!r}) — route writes through "
                f"utils/diskguard.py (GuardedWriter/append_line/"
                f"write_file_atomic for sinks, artifact_write for "
                f"artifacts) so a full disk is a classified, contained "
                f"event instead of a crash from inside a writer"))
    return findings
