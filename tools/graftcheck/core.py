"""Shared walker + finding/suppression model for the graftcheck rules.

One design decision carries the whole suite: every rule family consumes
the same :class:`Project`, which reads and ``ast.parse``s each package
file exactly ONCE.  Rule families never touch the filesystem themselves
(the params family reads the two docs files it audits, nothing else), so
adding a rule costs zero additional parses — the property the old
standalone ``lint_phase_scopes.py`` regex pass lacked.

Suppressions are inline comments::

    self._fh = open(path)   # graftcheck: disable=handle-close

``disable=a,b`` waives several rules on that line; ``disable=all``
waives every rule; ``# graftcheck: disable-file=<rule>`` anywhere in a
file waives the rule for the whole file.  Suppressed findings are not
dropped — they are reported and counted separately, so waivers stay
visible and cannot accumulate silently.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

SUPPRESS_RE = re.compile(r"#\s*graftcheck:\s*disable=([\w\-, ]+)")
FILE_SUPPRESS_RE = re.compile(r"#\s*graftcheck:\s*disable-file=([\w\-, ]+)")


@dataclass
class Finding:
    """One rule violation at a file/line."""

    rule: str
    path: str          # repo-root-relative, "/"-separated
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


def _parse_rules(raw: str) -> Set[str]:
    return {r.strip() for r in raw.split(",") if r.strip()}


class ModuleInfo:
    """One package file: text + AST (parsed once) + suppression map."""

    def __init__(self, path: pathlib.Path, root: pathlib.Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.tree = ast.parse(self.text, filename=str(path))
        self.suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        for lineno, line in enumerate(self.text.splitlines(), 1):
            m = FILE_SUPPRESS_RE.search(line)
            if m:
                self.file_suppressions |= _parse_rules(m.group(1))
                continue
            m = SUPPRESS_RE.search(line)
            if m:
                self.suppressions.setdefault(
                    lineno, set()).update(_parse_rules(m.group(1)))


class Project:
    """The analyzed tree: every package module, read+parsed once."""

    def __init__(self, root, pkg_rel: str = "lightgbm_tpu"):
        self.root = pathlib.Path(root).resolve()
        self.pkg_rel = str(pkg_rel)
        self.pkg = self.root / self.pkg_rel
        self.modules: List[ModuleInfo] = []
        self.parse_errors: List[Finding] = []
        for p in sorted(self.pkg.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            try:
                self.modules.append(ModuleInfo(p, self.root))
            except (SyntaxError, UnicodeDecodeError) as exc:
                self.parse_errors.append(Finding(
                    "parse-error", p.relative_to(self.root).as_posix(),
                    getattr(exc, "lineno", 1) or 1,
                    f"could not parse: {exc}"))
        self._by_rel = {m.rel: m for m in self.modules}
        self._index = None

    def module(self, rel: str) -> Optional[ModuleInfo]:
        return self._by_rel.get(rel)

    @property
    def index(self):
        """The lock/thread/call-graph index, built lazily and shared by
        every rule family that needs it (one build per run)."""
        if self._index is None:
            from .index import ProjectIndex
            self._index = ProjectIndex(self)
        return self._index

    def is_suppressed(self, f: Finding) -> bool:
        mod = self._by_rel.get(f.path)
        if mod is None:
            return False
        rules = mod.file_suppressions | mod.suppressions.get(f.line, set())
        return "all" in rules or f.rule in rules


# -- rule-family registry -----------------------------------------------

RULE_FAMILIES: Dict[str, Callable[[Project], List[Finding]]] = {}


def family(name: str):
    """Register a rule family: ``fn(project) -> [Finding]``."""
    def deco(fn):
        RULE_FAMILIES[name] = fn
        return fn
    return deco


@dataclass
class Report:
    """One analyzer run: live findings, suppressed findings, and the
    families that ran."""

    findings: List[Finding]
    suppressed: List[Finding]
    families: List[str]
    parse_errors: List[Finding] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.parse_errors) else 0

    def suppressed_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.suppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return dict(sorted(counts.items()))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "families": list(self.families),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "suppressed_counts": self.suppressed_counts(),
            "parse_errors": [f.to_dict() for f in self.parse_errors],
        }


def run_checks(root, families: Optional[Sequence[str]] = None,
               pkg_rel: str = "lightgbm_tpu",
               project: Optional[Project] = None) -> Report:
    """Run rule families over the tree at ``root`` (all families by
    default).  Raises ``ValueError`` for an unknown family name."""
    from . import rules  # noqa: F401 - registers the families

    if project is None:
        project = Project(root, pkg_rel=pkg_rel)
    names = list(families) if families else sorted(RULE_FAMILIES)
    unknown = [n for n in names if n not in RULE_FAMILIES]
    if unknown:
        raise ValueError(
            f"unknown rule families {unknown}; "
            f"known: {sorted(RULE_FAMILIES)}")
    collected: List[Finding] = []
    for n in names:
        collected.extend(RULE_FAMILIES[n](project))
    collected.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    live: List[Finding] = []
    waived: List[Finding] = []
    for f in collected:
        (waived if project.is_suppressed(f) else live).append(f)
    return Report(live, waived, names, parse_errors=project.parse_errors)
