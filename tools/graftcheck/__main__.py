"""CLI: ``python -m tools.graftcheck [--format=table|json]
[--rule=family,...] [--root=PATH]``.

Exit codes: 0 clean (suppressed findings allowed, but reported), 1 on
any unsuppressed finding or unparseable file, 2 on usage errors.  This
is the same contract the tier-1 test (tests/test_graftcheck.py) pins,
wired the same way the phase lint always was.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from .core import RULE_FAMILIES, run_checks


def main(argv=None) -> int:
    from . import rules  # noqa: F401 - registers families for --list-rules

    repo_root = pathlib.Path(__file__).resolve().parent.parent.parent
    p = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="Static analysis: lock discipline, jit tracer "
                    "safety, recompile hazards, thread/clock lifecycle, "
                    "phase taxonomy, parameter docs.")
    p.add_argument("--format", choices=("table", "json"), default="table")
    p.add_argument("--rule", action="append", metavar="FAMILY",
                   help="run only these rule families (comma-separable, "
                        "repeatable); default: all")
    p.add_argument("--root", default=str(repo_root),
                   help="repo root to analyze (default: this checkout)")
    p.add_argument("--pkg", default="lightgbm_tpu",
                   help="package dir under the root (default: "
                        "lightgbm_tpu)")
    p.add_argument("--list-rules", action="store_true",
                   help="list rule families and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULE_FAMILIES):
            print(name)
        return 0

    families = None
    if args.rule:
        families = [f.strip() for chunk in args.rule
                    for f in chunk.split(",") if f.strip()]
    try:
        report = run_checks(args.root, families=families,
                            pkg_rel=args.pkg)
    except ValueError as exc:
        print(f"graftcheck: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
        return report.exit_code

    for f in report.parse_errors:
        print(f.render(), file=sys.stderr)
    for f in report.findings:
        print(f.render(), file=sys.stderr)
    counts = report.suppressed_counts()
    waived = ", ".join(f"{k}={v}" for k, v in counts.items()) or "none"
    if report.findings or report.parse_errors:
        print(f"graftcheck: {len(report.findings)} finding(s), "
              f"{len(report.suppressed)} suppressed ({waived})",
              file=sys.stderr)
    else:
        print(f"graftcheck: clean ({len(report.families)} rule "
              f"families; suppressed waivers: {waived})")
        for f in report.suppressed:
            print(f"  waived: {f.render()}")
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
