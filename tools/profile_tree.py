"""Per-program device-time profile of a small training run.

Thin driver over the repo's own profiling path (``obs/devprof.py``, PR
16): arms devprof, trains a few boosting rounds through the public
``lgb.train`` surface, and renders the same table ``obs-report
--profile`` produces — per-program estimated device seconds with
roofline counters, the per-round host/device split, and transfer
volumes.  This replaced a one-off ``jax.profiler`` perfetto-trace
aggregator so there is exactly ONE profiling path to maintain; for
kernel-level op names beyond the program granularity, use
``jax.profiler.trace`` + perfetto directly.

Environment knobs::

    PROF_ROWS=200000 PROF_ROUNDS=20 PROF_DEVPROF=sample:4 \
        python tools/profile_tree.py

``PROF_DEVPROF`` defaults to ``full`` (every dispatch synced — highest
fidelity, fine for a profiling one-shot); use ``sample:N`` to measure
the production sampling mode itself.  ``LIGHTGBM_TPU_DEVPROF`` still
wins over everything, as everywhere.
"""

import os
import sys

import numpy as np

sys.path.insert(0, ".")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs import devprof, report  # noqa: E402

N = int(os.environ.get("PROF_ROWS", 100_000))
F = int(os.environ.get("PROF_FEATURES", 28))
ROUNDS = int(os.environ.get("PROF_ROUNDS", 10))
MODE = os.environ.get("PROF_DEVPROF", "full")


def main():
    rng = np.random.RandomState(0)
    # mildly informative features so splits are realistic (not uniform)
    X = rng.normal(size=(N, F)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1] + rng.normal(scale=1.5, size=N)
    y = (logit > 0).astype(np.float32)

    params = {
        "objective": "binary",
        "num_leaves": 63,
        "learning_rate": 0.1,
        "verbosity": -1,
        "devprof": MODE,   # LIGHTGBM_TPU_DEVPROF env still wins
    }
    booster = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=ROUNDS)
    booster.predict(X[:4096])

    print(report.render_profile_table(report.profile_summary(top_k=12)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
