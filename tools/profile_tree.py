"""Device-op profile of the ordered grower at 1M rows.

Traces a few in-loop iterations with jax.profiler and aggregates device op
durations from the generated perfetto trace — the ground-truth replacement
for stub ablations (which perturb control flow) and standalone microbenches
(which axon's dispatch replay cache poisons).
"""

import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from lightgbm_tpu.ops.grow import GrowParams  # noqa: E402
from lightgbm_tpu.ops.ordered_grow import grow_tree_ordered, pack_u8_words  # noqa: E402

N = int(os.environ.get("PROF_ROWS", 1 << 20))
F, B, L = 28, 255, 63
TRACE_DIR = "/tmp/jaxtrace"


def main():
    rng = np.random.RandomState(0)
    # mildly informative features so splits are realistic (not uniform)
    X = rng.normal(size=(N, 4)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1] + rng.normal(scale=1.5, size=N)
    y = jnp.asarray((logit > 0).astype(np.float32))
    binsm = rng.randint(0, B, size=(N, F)).astype(np.uint8)
    binsm[:, 0] = np.clip((X[:, 0] + 4) * 32, 0, B - 1).astype(np.uint8)
    binsm[:, 1] = np.clip((X[:, 1] + 4) * 32, 0, B - 1).astype(np.uint8)
    bins_rm = jnp.asarray(binsm)
    bins = bins_rm.T
    bins_words = jax.jit(pack_u8_words)(bins_rm)
    num_bin = jnp.full((F,), B, jnp.int32)
    is_cat = jnp.zeros((F,), bool)
    feat_mask = jnp.ones((F,), bool)
    w = jnp.ones((N,), jnp.float32)
    params = GrowParams(num_leaves=L, max_bin=B, min_data_in_leaf=50,
                        min_sum_hessian_in_leaf=1e-3)

    @jax.jit
    def grads(score):
        p = jax.nn.sigmoid(score)
        return p - y, p * (1 - p)

    def one(score):
        g, h = grads(score)
        _, _, delta = grow_tree_ordered(bins, num_bin, is_cat, feat_mask,
                                        g, h, w, jnp.float32(0.1), params,
                                        bins_rm=bins_rm,
                                        bins_words=bins_words)
        return score + delta

    score = jnp.zeros(N, jnp.float32)
    t0 = time.time()
    for _ in range(3):
        score = one(score)
    jax.block_until_ready(score)
    print(f"warm 3 iters: {time.time() - t0:.1f}s")

    t0 = time.time()
    for _ in range(5):
        score = one(score)
    jax.block_until_ready(score)
    print(f"steady: {(time.time() - t0) / 5 * 1e3:.1f} ms/tree")

    os.system(f"rm -rf {TRACE_DIR}")
    jax.profiler.start_trace(TRACE_DIR)
    for _ in range(3):
        score = one(score)
    jax.block_until_ready(score)
    jax.profiler.stop_trace()

    files = glob.glob(f"{TRACE_DIR}/**/*.trace.json.gz", recursive=True)
    print("trace files:", files)
    agg = defaultdict(float)
    cnt = defaultdict(int)
    total = 0.0
    for f in files:
        with gzip.open(f, "rt") as fh:
            data = json.load(fh)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            pid_name = ev.get("pid")
            name = ev.get("name", "")
            dur = ev.get("dur", 0) / 1e3  # ms
            cat = ev.get("args", {})
            # keep device lanes only (XLA Ops)
            tid = ev.get("tid", 0)
            if "tf_op" in cat or name.startswith("fusion") or True:
                agg[name[:80]] += dur
                cnt[name[:80]] += 1
    top = sorted(agg.items(), key=lambda kv: -kv[1])[:45]
    for name, ms in top:
        print(f"{ms:10.2f} ms  x{cnt[name]:5d}  {name}")


if __name__ == "__main__":
    main()
