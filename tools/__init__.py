"""Repo tooling.  This package marker exists so ``python -m
tools.graftcheck`` resolves from the repo root; the standalone scripts in
this directory (bench_regress.py, lint_phase_scopes.py, ...) keep running
by file path as before."""
