#!/usr/bin/env python
"""Static lint: keep the host (timetag) and device (named_scope) phase
taxonomies from drifting apart.

``utils/timetag.py`` accumulates host wall-clock under
``timetag.scope("GBDT::x")`` names; the jitted growers annotate device
ops with ``jax.named_scope("x")`` so LIGHTGBM_TPU_TRACE_DIR traces break
down by phase.  The two taxonomies only stay joinable (trace time
attributed back to the host account) if both match the declarations in
``lightgbm_tpu/obs/phases.py``.  Checks:

1. every ``timetag.scope("X")`` literal under lightgbm_tpu/ is declared
   in HOST_PHASES, and every declared host phase is used in code;
2. every ``jax.named_scope("X")`` in the jitted growers (ops/grow.py,
   ops/ordered_grow.py) is declared in DEVICE_PHASES, and vice versa;
3. DEVICE_PARENT maps every device phase onto a declared host phase, and
   every JITTED_HOST_PHASE is covered by at least one device phase —
   a rename on either side fails here instead of silently splitting the
   accounts.
4. every phase named in phases.py (host AND device) resolves through
   ``phases.span_series`` to a valid, UNIQUE Prometheus-safe histogram
   series name — the span/metrics namespace (obs/spans.py, obs/prom.py)
   and the phase taxonomy cannot diverge, and no two phases can silently
   alias onto one series.

``obs.span("X")`` sites count as host-phase users alongside
``timetag.scope("X")`` — the span API is the always-on successor and
feeds the same phase account (obs/spans.py).  So do the causal-tracing
call forms (``obs.trace_span("X")`` / ``obs.trace_begin("X")``,
obs/tracing.py): trace span names are the SAME taxonomy, so a name
invented at a tracing call site fails here instead of minting an
unregistered series.  The serving-fleet spans (``Serve::dispatch`` /
``Serve::reload`` / ``Serve::drain``, serve/fleet.py) and the
fault-tolerance spans (``Serve::hedge`` on the hedged-retry dispatch
path, ``Serve::eject`` / ``Serve::probe`` in the health watchdog,
serve/health.py) ride the same rule: declared in HOST_PHASES, used at
their call sites, one unique ``phase_seconds_*`` series each.

Runs standalone (``python tools/lint_phase_scopes.py``) and as a tier-1
test (tests/test_phase_lint.py).  phases.py is loaded by file path so
the lint never imports the package (or jax).
"""

from __future__ import annotations

import importlib.util
import pathlib
import re
import sys
from typing import Dict, List

ROOT = pathlib.Path(__file__).resolve().parent.parent
PKG = ROOT / "lightgbm_tpu"

SCOPE_RE = re.compile(
    r"(?:timetag\.scope|obs\.span|spans\.span"
    r"|obs\.trace_span|obs\.trace_begin|tracing\.span|TRACER\.(?:span|begin)"
    r")\(\s*[\"']([^\"']+)[\"']")
NAMED_RE = re.compile(r"jax\.named_scope\(\s*[\"']([^\"']+)[\"']")
SERIES_RE = re.compile(r"^phase_seconds_[a-z_][a-z0-9_]*$")

# the jitted paths carrying the device taxonomy: the growers plus the
# compiled-forest inference program (serve/forest.py)
DEVICE_FILES = ("ops/grow.py", "ops/ordered_grow.py", "serve/forest.py")


def _load_phases():
    spec = importlib.util.spec_from_file_location(
        "lightgbm_tpu_obs_phases", PKG / "obs" / "phases.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _scan(paths, rx) -> Dict[str, List[str]]:
    found: Dict[str, List[str]] = {}
    for p in paths:
        if not p.exists():
            # a missing device file shows up as its declared phases
            # being unused — a diagnosable error, not a crash
            continue
        for m in rx.finditer(p.read_text()):
            found.setdefault(m.group(1), []).append(
                str(p.relative_to(ROOT)))
    return found


def check() -> List[str]:
    """Return a list of violations (empty == clean)."""
    phases = _load_phases()
    errors: List[str] = []

    # obs/ declares the taxonomy (docstrings mention the call forms); it
    # is not a scope *user*
    host_files = [p for p in sorted(PKG.rglob("*.py"))
                  if "obs" not in p.relative_to(PKG).parts]
    host_used = _scan(host_files, SCOPE_RE)
    for name, sites in sorted(host_used.items()):
        if name not in phases.HOST_PHASES:
            errors.append(
                f"timetag.scope({name!r}) in {sites} is not declared in "
                f"obs/phases.py HOST_PHASES")
    for name in sorted(phases.HOST_PHASES - set(host_used)):
        errors.append(
            f"HOST_PHASES declares {name!r} but no timetag.scope uses it")

    dev_used = _scan([PKG / f for f in DEVICE_FILES], NAMED_RE)
    for name, sites in sorted(dev_used.items()):
        if name not in phases.DEVICE_PHASES:
            errors.append(
                f"jax.named_scope({name!r}) in {sites} is not declared in "
                f"obs/phases.py DEVICE_PHASES")
    for name in sorted(phases.DEVICE_PHASES - set(dev_used)):
        errors.append(
            f"DEVICE_PHASES declares {name!r} but no jax.named_scope in "
            f"{DEVICE_FILES} uses it")

    for name in sorted(phases.DEVICE_PHASES):
        parent = phases.DEVICE_PARENT.get(name)
        if parent is None:
            errors.append(f"DEVICE_PARENT has no mapping for {name!r}")
        elif parent not in phases.HOST_PHASES:
            errors.append(
                f"DEVICE_PARENT maps {name!r} -> {parent!r}, which is not "
                f"a declared host phase")
    covered = set(phases.DEVICE_PARENT.values())
    for name in sorted(phases.JITTED_HOST_PHASES - covered):
        errors.append(
            f"jitted host phase {name!r} has no device phase mapped onto "
            f"it — traces inside it would be unattributable")

    # -- 4: phase taxonomy <-> metrics namespace (obs/spans.py) ---------
    span_series = getattr(phases, "span_series", None)
    if span_series is None:
        errors.append("obs/phases.py no longer defines span_series() — "
                      "the span/metrics namespace is unmapped")
        return errors
    seen: Dict[str, str] = {}
    for name in sorted(phases.HOST_PHASES | phases.DEVICE_PHASES):
        series = span_series(name)
        if not SERIES_RE.match(series):
            errors.append(
                f"span_series({name!r}) = {series!r} is not a valid "
                f"phase histogram series name ({SERIES_RE.pattern})")
        if series in seen:
            errors.append(
                f"phases {seen[series]!r} and {name!r} collide onto the "
                f"same span series {series!r}")
        seen[series] = name
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(f"lint_phase_scopes: {e}", file=sys.stderr)
    if not errors:
        print("lint_phase_scopes: host/device phase taxonomies in sync")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
