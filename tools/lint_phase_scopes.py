#!/usr/bin/env python
"""Static lint: keep the host (timetag) and device (named_scope) phase
taxonomies from drifting apart.

``utils/timetag.py`` accumulates host wall-clock under
``timetag.scope("GBDT::x")`` names; the jitted growers annotate device
ops with ``jax.named_scope("x")`` so LIGHTGBM_TPU_TRACE_DIR traces break
down by phase.  The two taxonomies only stay joinable (trace time
attributed back to the host account) if both match the declarations in
``lightgbm_tpu/obs/phases.py``.  Checks:

1. every ``timetag.scope("X")`` literal under lightgbm_tpu/ is declared
   in HOST_PHASES, and every declared host phase is used in code;
2. every ``jax.named_scope("X")`` in the jitted growers (ops/grow.py,
   ops/ordered_grow.py) is declared in DEVICE_PHASES, and vice versa;
3. DEVICE_PARENT maps every device phase onto a declared host phase, and
   every JITTED_HOST_PHASE is covered by at least one device phase —
   a rename on either side fails here instead of silently splitting the
   accounts.
4. every phase named in phases.py (host AND device) resolves through
   ``phases.span_series`` to a valid, UNIQUE Prometheus-safe histogram
   series name — the span/metrics namespace (obs/spans.py, obs/prom.py)
   and the phase taxonomy cannot diverge, and no two phases can silently
   alias onto one series.

``obs.span("X")`` sites count as host-phase users alongside
``timetag.scope("X")`` — the span API is the always-on successor and
feeds the same phase account (obs/spans.py).  So do the causal-tracing
call forms (``obs.trace_span("X")`` / ``obs.trace_begin("X")``,
obs/tracing.py): trace span names are the SAME taxonomy, so a name
invented at a tracing call site fails here instead of minting an
unregistered series.  The serving-fleet spans (``Serve::dispatch`` /
``Serve::reload`` / ``Serve::drain``, serve/fleet.py) and the
fault-tolerance spans (``Serve::hedge`` on the hedged-retry dispatch
path, ``Serve::eject`` / ``Serve::probe`` in the health watchdog,
serve/health.py) ride the same rule: declared in HOST_PHASES, used at
their call sites, one unique ``phase_seconds_*`` series each.

Since the graftcheck suite landed, the implementation lives in
``tools/graftcheck/rules/phases.py`` as the ``phases`` rule family and
runs on the shared walker — one read+parse per file for ALL rule
families instead of a private scan.  This entry point is preserved:
``python tools/lint_phase_scopes.py`` (and tests/test_phase_lint.py)
behave exactly as before; phases.py is loaded by file path so the lint
never imports the package (or jax).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

ROOT = pathlib.Path(__file__).resolve().parent.parent
PKG = ROOT / "lightgbm_tpu"

sys.path.insert(0, str(ROOT))

from tools.graftcheck.rules import phases as _phases  # noqa: E402

# the shared regexes/constants, re-exported for callers and tests
SCOPE_RE = _phases.SCOPE_RE
NAMED_RE = _phases.NAMED_RE
SERIES_RE = _phases.SERIES_RE
DEVICE_FILES = _phases.DEVICE_FILES


def check() -> List[str]:
    """Return a list of violations (empty == clean)."""
    return _phases.scope_errors(ROOT, PKG)


def main() -> int:
    errors = check()
    for e in errors:
        print(f"lint_phase_scopes: {e}", file=sys.stderr)
    if not errors:
        print("lint_phase_scopes: host/device phase taxonomies in sync")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
