#!/usr/bin/env python
"""Benchmark regression gate: compare a fresh BENCH_*.json against a
named baseline and fail loudly on a throughput regression.

``bench.py`` prints one JSON line per run ({"metric", "value", "unit",
...}); the driver archives them as ``BENCH_rNN.json`` (either the bare
result object or the driver envelope whose ``tail``/``parsed`` fields
hold it).  This tool makes those files actionable:

    python tools/bench_regress.py --baseline BENCH_r05.json \
        --candidate /tmp/bench_new.json --threshold 5

exits 0 when the candidate's ``value`` is within ``--threshold`` percent
below the baseline (higher is always better here — both bench modes
report rates), 1 on a regression, 2 on unreadable/mismatched inputs.
The one-line JSON verdict on stdout carries both values and the delta so
a CI log shows the numbers, not just the exit code.  Intended CI shape
once a TPU runner exists (docs/OBSERVABILITY.md §Benchmark regression
gate):

    python bench.py > /tmp/bench_new.json
    python tools/bench_regress.py --baseline BENCH_r05.json \
        --candidate /tmp/bench_new.json --threshold 10

Mind the variance notes in docs/BENCH_NOTES_r03.md: the shared device
measured 5.9-7.5 it/s for identical code across a day, so gate with a
threshold wider than the observed window spread (the JSON's ``spread``
tail comment) or on a quiet runner.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional


def extract_result(path: str) -> Dict[str, Any]:
    """Load a bench result from either a bare bench.py JSON line or a
    driver envelope (``parsed`` field, or the last JSON object line of a
    ``tail`` transcript)."""
    with open(path) as fh:
        text = fh.read()
    obj = json.loads(text)
    if "value" in obj and "metric" in obj:
        return obj
    if isinstance(obj.get("parsed"), dict) and "value" in obj["parsed"]:
        return obj["parsed"]
    tail = obj.get("tail", "")
    result: Optional[Dict[str, Any]] = None
    for line in str(tail).splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "value" in cand and "metric" in cand:
                result = cand
    if result is None:
        raise ValueError(f"{path}: no bench result object found")
    return result


def compare(baseline: Dict[str, Any], candidate: Dict[str, Any],
            threshold_pct: float) -> Dict[str, Any]:
    """Verdict dict; ``ok`` is False when the candidate regressed more
    than ``threshold_pct`` percent below the baseline value."""
    if baseline.get("metric") != candidate.get("metric"):
        raise ValueError(
            f"metric mismatch: baseline {baseline.get('metric')!r} vs "
            f"candidate {candidate.get('metric')!r} — comparing different "
            f"workloads is not a regression check")
    base = float(baseline["value"])
    cand = float(candidate["value"])
    if base <= 0:
        raise ValueError(f"baseline value {base} is not a positive rate")
    delta_pct = (cand - base) / base * 100.0
    return {
        "metric": baseline.get("metric"),
        "unit": baseline.get("unit"),
        "baseline": base,
        "candidate": cand,
        "delta_pct": round(delta_pct, 3),
        "threshold_pct": float(threshold_pct),
        "ok": delta_pct >= -float(threshold_pct),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on >threshold%% bench throughput regression")
    ap.add_argument("--baseline", required=True,
                    help="baseline BENCH_*.json (bare result or driver "
                         "envelope)")
    ap.add_argument("--candidate", required=True,
                    help="fresh bench.py output JSON to check")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="allowed regression in percent (default 5)")
    args = ap.parse_args(argv)
    try:
        verdict = compare(extract_result(args.baseline),
                          extract_result(args.candidate), args.threshold)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as exc:
        print(f"bench_regress: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(verdict))
    if not verdict["ok"]:
        print(f"bench_regress: REGRESSION {verdict['delta_pct']:+.2f}% "
              f"(threshold -{args.threshold:g}%) on {verdict['metric']}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
