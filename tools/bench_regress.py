#!/usr/bin/env python
"""Benchmark regression gate: compare a fresh BENCH_*.json against a
named baseline and fail loudly on a throughput regression.

``bench.py`` prints one JSON line per run ({"metric", "value", "unit",
...}); the driver archives them as ``BENCH_rNN.json`` (either the bare
result object or the driver envelope whose ``tail``/``parsed`` fields
hold it).  This tool makes those files actionable:

    python tools/bench_regress.py --baseline BENCH_r05.json \
        --candidate /tmp/bench_new.json --threshold 5

exits 0 when the candidate's ``value`` is within ``--threshold`` percent
below the baseline (higher is always better here — both bench modes
report rates), 1 on a regression, 2 on unreadable/mismatched inputs.
The one-line JSON verdict on stdout carries both values and the delta so
a CI log shows the numbers, not just the exit code.

``--warmup-threshold <pct>`` additionally gates the WARMUP tax (the XLA
compile seconds before the timed windows): the candidate's COLD warmup
may exceed the baseline's by at most that many percent.  Since round 7
bench.py splits warmup into ``warmup_cold_s`` (first boot, compiles) and
``warmup_warm_s`` (second booster, compile caches hot); the gate reads
``warmup_cold_s`` and falls back to ``warmup_s`` (always a cold number,
first-class key since round 6) so pre-r07 baselines compare like with
like; for even older baselines the value is recovered from the
``warmup_s=...`` field of the driver envelope's tail comment.  The warm
number rides along in the verdict uninspected.  Lower warmup is always fine — the gate is
one-sided, like the throughput gate.  Mind that warmup variance dwarfs
throughput variance (34-321 s across BENCH_r02-r05 for identical code:
remote-AOT service load + persistent-cache hits); gate wide, or pin the
environment first.  Intended CI shape
once a TPU runner exists (docs/OBSERVABILITY.md §Benchmark regression
gate):

    python bench.py > /tmp/bench_new.json
    python tools/bench_regress.py --baseline BENCH_r05.json \
        --candidate /tmp/bench_new.json --threshold 10

Mind the variance notes in docs/BENCH_NOTES_r03.md: the shared device
measured 5.9-7.5 it/s for identical code across a day, so gate with a
threshold wider than the observed window spread (the JSON's ``spread``
tail comment) or on a quiet runner.

Round 8's ``bench.py --mode predict --concurrency N`` adds ``fleet`` /
``concurrency`` keys (per-replica-count rows/sec + shed rate); they pass
through into the verdict informationally on whichever side carries them
and are never required — old baselines keep comparing.  Round 9 adds an
``availability`` block the same way (``serve_retries_total`` /
``serve_ejections_total`` / ``serve_deadline_expired_total`` deltas over
the bench run): informational, never gated, never required.

``--program-threshold <pct>`` gates PER-PROGRAM device seconds from the
``profile`` block (PR 16, obs/devprof.py): for every XLA program present
on both sides with a positive baseline ``device_seconds_est``, the
candidate may exceed the baseline by at most that many percent — the
instrument ROADMAP item 1's fused-vs-ordered A/B needs ("the end-to-end
rate held, but grow_tree got 40% slower" fails loudly instead of hiding
inside the aggregate).  Both bench runs must profile (run with
LIGHTGBM_TPU_DEVPROF=sample:N; sampling correction makes estimates
comparable across different N).  When either side carries no profiled
programs — every pre-r16 baseline — the per-program gate records a note
and passes: old baselines keep comparing, exactly like the other
informational blocks, and the ``profile``/``device`` summaries ride
along per side when present.

``--latency-threshold <pct>`` gates PER-BATCH p99 latency from the
``latency_sweep`` block (PR 20, the fused Pallas forest-walk kernel):
``bench.py --mode predict`` times single calls at batch 1/16/64/256 per
serving strategy and records p50/p99 milliseconds.  For every
(strategy, batch) point present on both sides the candidate's p99 may
exceed the baseline's by at most that many percent — the end-to-end
rows/sec gate averages tail latency away, and tail latency is exactly
what the fused walk exists to shrink.  When either side lacks the block
(pre-r20 baselines, --mode train runs) the gate records a note and
passes, like the per-program gate.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, Optional


def extract_result(path: str) -> Dict[str, Any]:
    """Load a bench result from either a bare bench.py JSON line or a
    driver envelope (``parsed`` field, or the last JSON object line of a
    ``tail`` transcript).  ``warmup_s`` is folded in from the tail's
    ``warmup_s=...`` stderr comment when the result object itself does
    not carry it (pre-round-6 BENCH files)."""
    with open(path) as fh:
        text = fh.read()
    obj = json.loads(text)
    if "value" in obj and "metric" in obj:
        return obj
    result: Optional[Dict[str, Any]] = None
    if isinstance(obj.get("parsed"), dict) and "value" in obj["parsed"]:
        result = dict(obj["parsed"])
    tail = str(obj.get("tail", ""))
    if result is None:
        for line in tail.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "value" in cand and "metric" in cand:
                    result = cand
    if result is None:
        raise ValueError(f"{path}: no bench result object found")
    if "warmup_s" not in result:
        m = re.search(r"\bwarmup_s=([0-9]+(?:\.[0-9]+)?)", tail)
        if m:
            result["warmup_s"] = float(m.group(1))
    return result


def compare(baseline: Dict[str, Any], candidate: Dict[str, Any],
            threshold_pct: float,
            warmup_threshold_pct: Optional[float] = None,
            program_threshold_pct: Optional[float] = None,
            latency_threshold_pct: Optional[float] = None) -> Dict[str, Any]:
    """Verdict dict; ``ok`` is False when the candidate regressed more
    than ``threshold_pct`` percent below the baseline value, (with a
    warmup threshold) when its warmup exceeds the baseline's by more
    than ``warmup_threshold_pct`` percent, (with a program threshold)
    when any program's estimated device seconds grew by more than
    ``program_threshold_pct`` percent — skipped with a note when either
    side carries no profiled programs — or (with a latency threshold)
    when any ``latency_sweep`` p99 grew by more than
    ``latency_threshold_pct`` percent at any (strategy, batch) point
    present on both sides — likewise skipped with a note when either
    side lacks the block."""
    if baseline.get("metric") != candidate.get("metric"):
        raise ValueError(
            f"metric mismatch: baseline {baseline.get('metric')!r} vs "
            f"candidate {candidate.get('metric')!r} — comparing different "
            f"workloads is not a regression check")
    base = float(baseline["value"])
    cand = float(candidate["value"])
    if base <= 0:
        raise ValueError(f"baseline value {base} is not a positive rate")
    delta_pct = (cand - base) / base * 100.0
    verdict = {
        "metric": baseline.get("metric"),
        "unit": baseline.get("unit"),
        "baseline": base,
        "candidate": cand,
        "delta_pct": round(delta_pct, 3),
        "threshold_pct": float(threshold_pct),
        "ok": delta_pct >= -float(threshold_pct),
    }
    if warmup_threshold_pct is not None:
        # round 7 split warmup into warmup_cold_s (first-boot compile
        # tax) and warmup_warm_s (steady-state, compile caches hot); the
        # gate compares COLD with cold — pre-r07 baselines carry only
        # warmup_s, which was always a cold measurement, so falling back
        # to it keeps the comparison like-with-like.
        wb = baseline.get("warmup_cold_s", baseline.get("warmup_s"))
        wc = candidate.get("warmup_cold_s", candidate.get("warmup_s"))
        if wb is None or wc is None:
            # a warmup gate over sides that never measured warmup would
            # silently pass forever — that is an input error, not a pass
            missing = [side for side, w in (("baseline", wb),
                                            ("candidate", wc)) if w is None]
            raise ValueError(
                f"--warmup-threshold given but {' and '.join(missing)} "
                f"carr{'y' if len(missing) > 1 else 'ies'} no warmup_s "
                f"(neither as a JSON key nor in the tail comment)")
        wb, wc = float(wb), float(wc)
        wdelta = ((wc - wb) / wb * 100.0) if wb > 0 else \
            (0.0 if wc <= 0 else float("inf"))
        verdict.update({
            "warmup_baseline_s": wb,
            "warmup_candidate_s": wc,
            "warmup_delta_pct": round(wdelta, 3) if wdelta != float("inf")
            else None,
            "warmup_threshold_pct": float(warmup_threshold_pct),
            "warmup_ok": wdelta <= float(warmup_threshold_pct),
        })
        # informational: the warm-restart warmup, when both sides have it
        # (r07+); not gated — its whole point is to be near zero, and the
        # cold gate already guards the compile tax
        for side, obj in (("baseline", baseline), ("candidate", candidate)):
            if obj.get("warmup_warm_s") is not None:
                verdict[f"warmup_warm_{side}_s"] = float(obj["warmup_warm_s"])
        verdict["ok"] = verdict["ok"] and verdict["warmup_ok"]
    if program_threshold_pct is not None:
        bp = (baseline.get("profile") or {}).get("programs") or {}
        cp = (candidate.get("profile") or {}).get("programs") or {}
        deltas: Dict[str, Any] = {}
        progs_ok = True
        for prog in sorted(set(bp) & set(cp)):
            b = (bp[prog] or {}).get("device_seconds_est")
            c = (cp[prog] or {}).get("device_seconds_est")
            if b is None or c is None or float(b) <= 0:
                continue
            d = (float(c) - float(b)) / float(b) * 100.0
            ok = d <= float(program_threshold_pct)
            deltas[prog] = {"baseline_s": round(float(b), 6),
                            "candidate_s": round(float(c), 6),
                            "delta_pct": round(d, 3), "ok": ok}
            progs_ok = progs_ok and ok
        verdict["program_threshold_pct"] = float(program_threshold_pct)
        verdict["programs_delta"] = deltas
        if not bp or not cp:
            # pre-r16 BENCH files (or runs with devprof off) carry no
            # profiled programs — the gate must not fail them, or every
            # historical baseline stops comparing; record WHY it passed
            missing = [s for s, p in (("baseline", bp),
                                      ("candidate", cp)) if not p]
            verdict["programs_ok"] = True
            verdict["programs_note"] = (
                f"profile programs missing on {' and '.join(missing)} — "
                f"per-program gate skipped (run bench with "
                f"LIGHTGBM_TPU_DEVPROF to gate)")
        else:
            verdict["programs_ok"] = progs_ok
            verdict["ok"] = verdict["ok"] and progs_ok
    if latency_threshold_pct is not None:
        # PR 20: bench.py --mode predict emits a ``latency_sweep`` block
        # (per serving strategy, per batch size: p50_ms/p99_ms over
        # single-call dispatches).  The gate is on p99 — tail latency is
        # what the fused walk kernel exists to shrink, and an end-to-end
        # rows/sec gate averages it away.  Compared per (strategy, batch)
        # point present on BOTH sides; one-sided, like every other gate.
        bl = (baseline.get("latency_sweep") or {}).get("strategies") or {}
        cl = (candidate.get("latency_sweep") or {}).get("strategies") or {}
        ldeltas: Dict[str, Any] = {}
        lat_ok = True
        for strat in sorted(set(bl) & set(cl)):
            bpts, cpts = bl[strat] or {}, cl[strat] or {}
            for batch in sorted(set(bpts) & set(cpts), key=int):
                b = (bpts[batch] or {}).get("p99_ms")
                c = (cpts[batch] or {}).get("p99_ms")
                if b is None or c is None or float(b) <= 0:
                    continue
                d = (float(c) - float(b)) / float(b) * 100.0
                ok = d <= float(latency_threshold_pct)
                ldeltas[f"{strat}/{batch}"] = {
                    "baseline_p99_ms": round(float(b), 4),
                    "candidate_p99_ms": round(float(c), 4),
                    "delta_pct": round(d, 3), "ok": ok}
                lat_ok = lat_ok and ok
        verdict["latency_threshold_pct"] = float(latency_threshold_pct)
        verdict["latency_delta"] = ldeltas
        if not bl or not cl:
            # pre-r20 BENCH files (or --mode train runs) carry no latency
            # sweep — the gate must not fail them, or every historical
            # baseline stops comparing; record WHY it passed
            missing = [s for s, p in (("baseline", bl),
                                      ("candidate", cl)) if not p]
            verdict["latency_ok"] = True
            verdict["latency_note"] = (
                f"latency_sweep missing on {' and '.join(missing)} — "
                f"latency gate skipped (run bench.py --mode predict to "
                f"gate)")
        else:
            verdict["latency_ok"] = lat_ok
            verdict["ok"] = verdict["ok"] and lat_ok
    # informational: the serving-fleet scaling curve (round 8's
    # ``bench.py --mode predict --concurrency N`` adds ``fleet`` /
    # ``concurrency`` keys) rides along in the verdict per side when
    # present — not gated (replica counts vary per box), never an error
    # when absent (pre-r08 baselines)
    for side, obj in (("baseline", baseline), ("candidate", candidate)):
        fleet = obj.get("fleet")
        if isinstance(fleet, dict) and fleet:
            verdict[f"fleet_{side}_rows_per_sec"] = {
                r: blk.get("rows_per_sec")
                for r, blk in sorted(fleet.items(),
                                     key=lambda kv: int(kv[0]))
                if isinstance(blk, dict)}
            shed = {r: blk.get("shed_rate") for r, blk in fleet.items()
                    if isinstance(blk, dict) and blk.get("shed_rate")}
            if shed:
                verdict[f"fleet_{side}_shed_rate"] = shed
        # round 9: serving availability counters (hedged retries,
        # replica ejections, deadline sheds) ride along informationally —
        # a chaos-y bench run should show its fault bill in the verdict,
        # but replica health is environment-dependent, so never gated
        avail = obj.get("availability")
        if isinstance(avail, dict) and avail:
            verdict[f"availability_{side}"] = avail
        # PR 13: a train run that QUARANTINED bad rows says so in the
        # verdict — a throughput number over a partially-skipped
        # dataset carries its asterisk, but dirt volume is data-
        # dependent, so never gated
        bad = obj.get("bad_rows")
        if isinstance(bad, dict) and bad:
            verdict[f"bad_rows_{side}"] = bad
        # PR 15: resource bill (docs/FAULT_TOLERANCE.md §Resource
        # exhaustion) — estimated vs measured peak bytes, degrade-ladder
        # steps taken, sink write errors.  Informational: degrade steps
        # are budget-dependent, never gated, never required (old
        # baselines keep comparing)
        res = obj.get("resource")
        if isinstance(res, dict) and res:
            verdict[f"resource_{side}"] = res
        # PR 14: wide-sparse training bill (docs/SPARSE.md) — EFB bundle
        # shrinkage, screening's active-feature trajectory, and the run's
        # AUC ride along informationally so an A/B ctrlike comparison
        # (bundling/screening on vs off) shows its accuracy asterisk;
        # never gated, never required (old baselines keep comparing)
        # PR 18: piece-wise linear trees bill (docs/LINEAR_TREES.md) —
        # trees-to-target vs the constant run, per-round fit seconds,
        # leaf-fit fallback rate.  Informational: accuracy trade-offs are
        # workload-dependent, never gated, never required
        # PR 19: drift observatory bill (docs/OBSERVABILITY.md §Drift) —
        # window PSI summary + collector compute seconds from --mode
        # predict.  Informational: old baselines have no drift block
        for key in ("efb", "screening", "linear", "drift"):
            blk = obj.get(key)
            if isinstance(blk, dict) and blk:
                verdict[f"{key}_{side}"] = blk
        if obj.get("auc") is not None:
            verdict[f"auc_{side}"] = float(obj["auc"])
        # PR 16: device-time attribution summary + hardware identity
        # (bench.py `profile`/`device` blocks) — informational per side;
        # the gated view lives under programs_delta when
        # --program-threshold is given
        prof = obj.get("profile")
        if isinstance(prof, dict) and prof:
            verdict[f"profile_{side}"] = {
                "mode": prof.get("mode"),
                "device_seconds_est_total":
                    prof.get("device_seconds_est_total"),
                "rounds": prof.get("rounds"),
            }
        dev = obj.get("device")
        if isinstance(dev, dict) and dev:
            verdict[f"device_{side}"] = {
                "platform": dev.get("platform"),
                "device_kind": dev.get("device_kind"),
                "jax_version": dev.get("jax_version"),
            }
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on >threshold%% bench throughput regression")
    ap.add_argument("--baseline", required=True,
                    help="baseline BENCH_*.json (bare result or driver "
                         "envelope)")
    ap.add_argument("--candidate", required=True,
                    help="fresh bench.py output JSON to check")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="allowed regression in percent (default 5)")
    ap.add_argument("--warmup-threshold", type=float, default=None,
                    help="also gate warmup_s: allowed warmup INCREASE in "
                         "percent over the baseline (off by default)")
    ap.add_argument("--program-threshold", type=float, default=None,
                    help="also gate per-program device seconds from the "
                         "profile block: allowed INCREASE in percent per "
                         "XLA program (off by default; skipped with a "
                         "note when either side has no profile data)")
    ap.add_argument("--latency-threshold", type=float, default=None,
                    help="also gate per-batch p99 latency from the "
                         "latency_sweep block: allowed INCREASE in "
                         "percent per (strategy, batch) point (off by "
                         "default; skipped with a note when either side "
                         "has no latency sweep)")
    args = ap.parse_args(argv)
    try:
        verdict = compare(extract_result(args.baseline),
                          extract_result(args.candidate), args.threshold,
                          warmup_threshold_pct=args.warmup_threshold,
                          program_threshold_pct=args.program_threshold,
                          latency_threshold_pct=args.latency_threshold)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as exc:
        print(f"bench_regress: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(verdict))
    if not verdict["ok"]:
        if not verdict.get("warmup_ok", True):
            print(f"bench_regress: WARMUP REGRESSION "
                  f"{verdict['warmup_candidate_s']:g}s vs baseline "
                  f"{verdict['warmup_baseline_s']:g}s "
                  f"(threshold +{args.warmup_threshold:g}%)",
                  file=sys.stderr)
        if not verdict.get("programs_ok", True):
            worst = max(
                (d for d in verdict.get("programs_delta", {}).items()
                 if not d[1]["ok"]),
                key=lambda d: d[1]["delta_pct"])
            print(f"bench_regress: PROGRAM REGRESSION {worst[0]} "
                  f"{worst[1]['delta_pct']:+.2f}% device time "
                  f"(threshold +{args.program_threshold:g}%)",
                  file=sys.stderr)
        if not verdict.get("latency_ok", True):
            worst = max(
                (d for d in verdict.get("latency_delta", {}).items()
                 if not d[1]["ok"]),
                key=lambda d: d[1]["delta_pct"])
            print(f"bench_regress: LATENCY REGRESSION {worst[0]} p99 "
                  f"{worst[1]['delta_pct']:+.2f}% "
                  f"(threshold +{args.latency_threshold:g}%)",
                  file=sys.stderr)
        if verdict["delta_pct"] < -args.threshold:
            print(f"bench_regress: REGRESSION {verdict['delta_pct']:+.2f}% "
                  f"(threshold -{args.threshold:g}%) on {verdict['metric']}",
                  file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
