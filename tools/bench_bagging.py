"""Measure the bagging-compaction speedup on TPU (VERDICT round-2 item 5:
bagging_fraction=0.25, bagging_freq=1 must train >= 2.5x faster trees
than full-data at 1M).

    python tools/bench_bagging.py [rows]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def run(num_data, bagging):
    import jax
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from bench import make_higgs_like

    X, y = make_higgs_like(num_data)
    params = {"objective": "binary", "metric": "auc",
              "is_training_metric": True,
              "num_leaves": 63, "max_bin": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 50,
              "num_iterations": 40}
    if bagging:
        params.update({"bagging_fraction": 0.25, "bagging_freq": 1,
                       "bagging_seed": 7})
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, y, max_bin=255, min_data_in_leaf=50)
    booster = GBDT(cfg, ds)
    warm = int(os.environ.get("BAG_WARMUP", 3))
    timed = int(os.environ.get("BAG_ITERS", 12))
    for _ in range(warm):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_data.score)
    t0 = time.time()
    for _ in range(timed):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_data.score)
    dt = (time.time() - t0) / timed
    auc = booster.eval_metrics().get("training", {}).get("auc")
    return dt, auc


def main():
    import jax
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_COMPILATION_CACHE_DIR",
                       "/tmp/lightgbm_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    dt_full, auc_full = run(rows, bagging=False)
    dt_bag, auc_bag = run(rows, bagging=True)
    print(f"full    : {dt_full * 1e3:8.1f} ms/iter")
    print(f"bag 0.25: {dt_bag * 1e3:8.1f} ms/iter  "
          f"speedup {dt_full / dt_bag:.2f}x")
    print(f"auc full={auc_full} bag={auc_bag}")


if __name__ == "__main__":
    main()
