"""Standalone TPU compile+timing probe for dynamic-grid hist kernel
variants.  Chained in-loop timing (axon replay-safe)."""

import sys
import time
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")
from lightgbm_tpu.ops.ordered_grow import pack_u8_words  # noqa: E402

N = 1 << 20
F, B = 28, 256
W = 7


def make_variant(name, nb):
    if name == "laneconcat":
        def kernel(s_ref, *refs, nb=nb):
            bins_refs = refs[:W]
            dig_refs = refs[W:W + 3]
            out_ref, acc_ref = refs[W + 3], refs[W + 4]
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)
            shift, scnt = s_ref[1], s_ref[2]
            row = jax.lax.broadcasted_iota(jnp.int32, (nb, 1), 0) + i * nb
            live = (row >= shift) & (row < shift + scnt)
            cols = []
            for j in range(9):
                b = (dig_refs[j // 4][:] >> (8 * (j % 4))) & 0xFF
                cols.append((b - ((b & 0x80) << 1))[:, None])
            dig = jnp.where(live, jnp.concatenate(cols, axis=1),
                            0).astype(jnp.int8)
            iota = jax.lax.broadcasted_iota(jnp.int32, (nb, B), 1)
            for f in range(F):
                b_f = ((bins_refs[f // 4][:] >> (8 * (f % 4))) & 0xFF)[:, None]
                onehot = (b_f == iota).astype(jnp.int8)
                part = jax.lax.dot_general(
                    dig, onehot, dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                acc_ref[f] += part

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]
        return kernel
    if name == "subconcat_T":
        def kernel(s_ref, *refs, nb=nb):
            bins_refs = refs[:W]
            dig_refs = refs[W:W + 3]
            out_ref, acc_ref = refs[W + 3], refs[W + 4]
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)
            shift, scnt = s_ref[1], s_ref[2]
            row = jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1) + i * nb
            live = (row >= shift) & (row < shift + scnt)
            rows9 = []
            for j in range(9):
                b = (dig_refs[j // 4][:] >> (8 * (j % 4))) & 0xFF
                rows9.append((b - ((b & 0x80) << 1))[None, :])
            dig_t = jnp.where(live, jnp.concatenate(rows9, axis=0),
                              0).astype(jnp.int8)          # [9, nb]
            dig = dig_t.T                                   # [nb, 9]
            iota = jax.lax.broadcasted_iota(jnp.int32, (nb, B), 1)
            for f in range(F):
                b_f = ((bins_refs[f // 4][:] >> (8 * (f % 4))) & 0xFF)[:, None]
                onehot = (b_f == iota).astype(jnp.int8)
                part = jax.lax.dot_general(
                    dig, onehot, dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                acc_ref[f] += part

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]
        return kernel
    if name == "digmat":
        # digits as a separate [S, 9] i8 2-D input (no in-kernel unpack)
        def kernel(s_ref, *refs, nb=nb):
            bins_refs = refs[:W]
            dig_ref = refs[W]
            out_ref, acc_ref = refs[W + 1], refs[W + 2]
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)
            shift, scnt = s_ref[1], s_ref[2]
            row = jax.lax.broadcasted_iota(jnp.int32, (nb, 1), 0) + i * nb
            live = (row >= shift) & (row < shift + scnt)
            dig = jnp.where(live, dig_ref[:, :], 0)
            iota = jax.lax.broadcasted_iota(jnp.int32, (nb, B), 1)
            for f in range(F):
                b_f = ((bins_refs[f // 4][:] >> (8 * (f % 4))) & 0xFF)[:, None]
                onehot = (b_f == iota).astype(jnp.int8)
                part = jax.lax.dot_general(
                    dig, onehot, dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                acc_ref[f] += part

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]
        return kernel
    raise ValueError(name)


def run(name, nb, with_dig_input):
    rng = np.random.RandomState(0)
    bins_rm = jnp.asarray(rng.randint(0, B - 1, size=(N, F)), jnp.uint8)
    digits = jnp.asarray(rng.randint(-128, 127, size=(N, 9)), jnp.int8)
    bw = jax.jit(pack_u8_words)(bins_rm)
    dw = jax.jit(pack_u8_words)(
        jax.lax.bitcast_convert_type(digits, jnp.uint8))
    kernel = make_variant(name, nb)

    n_in = W + (1 if with_dig_input else 3)
    in_specs = [pl.BlockSpec((nb,), lambda i, s: (s[0] + i,))
                for _ in range(W)]
    if with_dig_input:
        in_specs += [pl.BlockSpec((nb, 9), lambda i, s: (s[0] + i, 0))]
    else:
        in_specs += [pl.BlockSpec((nb,), lambda i, s: (s[0] + i,))
                     for _ in range(3)]

    @jax.jit
    def call(off, scnt, *ops):
        off0 = off // nb
        shift = off - off0 * nb
        nblocks = jnp.maximum((shift + scnt + nb - 1) // nb, 1)
        scalars = jnp.stack([off0, shift, scnt]).astype(jnp.int32)
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(nblocks,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((F, 9, B), lambda i, s: (0, 0, 0)),
            scratch_shapes=[pltpu.VMEM((F, 9, B), jnp.int32)])
        return pl.pallas_call(
            kernel, grid_spec=gs,
            out_shape=jax.ShapeDtypeStruct((F, 9, B), jnp.int32))(
                scalars, *ops)

    ops = bw + ((digits,) if with_dig_input else dw)

    @jax.jit
    def loop(off):
        def body(k, carry):
            off, acc = carry
            o = call(off, jnp.int32(N // 2), *ops)
            return (o[0, 0, 0] % 128, acc + o[0, 0, 1])
        return jax.lax.fori_loop(0, 10, body, (off, jnp.int32(0)))

    try:
        t0 = time.time()
        r = jax.block_until_ready(loop(jnp.int32(5)))
        ct = time.time() - t0
        t0 = time.time()
        r = jax.block_until_ready(loop(r[0]))
        dt = (time.time() - t0) / 10
        rows = N // 2
        print(f"{name:14s} nb={nb:5d}: compile {ct:5.1f}s  "
              f"{dt * 1e3:7.2f} ms/call  {dt / rows * 1e9:6.2f} ns/row")
    except Exception as e:
        msg = str(e).split("\n")[0][:140]
        print(f"{name:14s} nb={nb:5d}: FAIL {msg}")


if __name__ == "__main__":
    for name, nb, wd in [("laneconcat", 2048, False),
                         ("laneconcat", 4096, False),
                         ("subconcat_T", 8192, False),
                         ("digmat", 8192, True),
                         ("digmat", 4096, True)]:
        run(name, nb, wd)
