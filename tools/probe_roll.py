"""De-risk the round-4 Mosaic partition kernel: does pltpu.roll compile
(static + dynamic shifts), and what does a bitonic-style chain of
28 x (roll + compare + 12 selects) cost per row?  In-loop chained timing
(axon replay-safe).  See docs/BENCH_NOTES_r03.md 'Round-4 lever'."""

import sys
import time
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NB = 2048
WORDS = 12
STAGES = 28


def kernel(x_ref, out_ref):
    # x: [WORDS, NB] i32; emulate a stable-0/1-bitonic stage chain:
    # per stage: key roll + compare + per-word roll/select
    words = [x_ref[w, :] for w in range(WORDS)]
    key = words[0]
    for s in range(STAGES):
        shift = 1 << (s % 7)
        k_sh = pltpu.roll(key, shift, 0)
        take = k_sh < key
        new_words = []
        for w in range(WORDS):
            w_sh = pltpu.roll(words[w], shift, 0)
            new_words.append(jnp.where(take, w_sh, words[w]))
        words = new_words
        key = words[0]
    for w in range(WORDS):
        out_ref[w, :] = words[w]


def main():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(-2**31, 2**31 - 1, (WORDS, NB), np.int64)
                    .astype(np.int32))

    call = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((WORDS, NB), lambda: (0, 0))],
        out_specs=pl.BlockSpec((WORDS, NB), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((WORDS, NB), jnp.int32),
    )

    @jax.jit
    def loop(x):
        def body(_, acc):
            return call(acc) ^ 1
        return jax.lax.fori_loop(0, 50, body, x)

    try:
        t0 = time.time()
        out = jax.block_until_ready(loop(x))
        print(f"compile+run {time.time() - t0:.1f}s")
        t0 = time.time()
        out = jax.block_until_ready(loop(out))
        dt = (time.time() - t0) / 50
        print(f"roll-chain kernel: {dt * 1e6:8.1f} us/block  "
              f"{dt / NB * 1e9:6.2f} ns/row "
              f"({STAGES} stages x {WORDS} words)")
    except Exception as e:
        print("FAIL:", str(e).split(chr(10))[0][:200])


if __name__ == "__main__":
    main()
