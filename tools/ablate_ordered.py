"""In-loop ablation harness for the ordered grower (run on the TPU box).

Usage: python tools/ablate_ordered.py [variant ...]

Variants stub one stage of ops/ordered_grow.py at a time and re-time the
WHOLE tree in a data-dependent loop (g depends on the previous delta), so
axon's dispatch caching cannot short-circuit anything (see
docs/BENCH_NOTES_r02.md methodology warning).  Costs are read as
differences between variants, not absolutes.
"""

from __future__ import annotations

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from lightgbm_tpu.ops.grow import GrowParams  # noqa: E402

N = int(1e6)
F = 28
B = 255
L = 63
ITERS = 8

VARIANT = set(sys.argv[1:]) or {"base"}


def patched_grow():
    """Import ordered_grow with stage stubs applied per VARIANT."""
    import lightgbm_tpu.ops.ordered_grow as og
    import importlib
    importlib.reload(og)

    if "nokeygather" in VARIANT or "nogather" in VARIANT:
        # replace the [P, F] row gather feeding the key with a contiguous
        # slice of the same shape (wrong values, same downstream costs)
        real_take = jnp.take

        def fake_take(arr, idx, axis=None, **kw):
            if axis == 0 and idx.ndim == 1 and arr.ndim == 2:
                return jax.lax.dynamic_slice(
                    arr, (idx[0] % 128, 0), (idx.shape[0], arr.shape[1]))
            return real_take(arr, idx, axis=axis, **kw)
        og.jnp = type(sys)("jnp_patch")
        og.jnp.__dict__.update(jnp.__dict__)
        og.jnp.take = fake_take
    if "nosort" in VARIANT:
        real_sort = jax.lax.sort

        def fake_sort(operands, num_keys=1, is_stable=False):
            return operands
        og.jax = type(sys)("jax_patch")
        og.jax.__dict__.update(jax.__dict__)
        og.jax.lax = type(sys)("lax_patch")
        og.jax.lax.__dict__.update(jax.lax.__dict__)
        og.jax.lax.sort = fake_sort
    return og


def main():
    og = patched_grow()
    rng = np.random.RandomState(0)
    bins_rm = jnp.asarray(rng.randint(0, B, size=(N, F)), jnp.uint8)
    bins = bins_rm.T
    num_bin = jnp.full((F,), B, jnp.int32)
    is_cat = jnp.zeros((F,), bool)
    feat_mask = jnp.ones((F,), bool)
    w = jnp.ones((N,), jnp.float32)
    y = jnp.asarray(rng.randint(0, 2, size=N), jnp.float32)
    params = GrowParams(num_leaves=L, max_bin=B, min_data_in_leaf=50,
                        min_sum_hessian_in_leaf=1e-3)

    score = jnp.zeros(N, jnp.float32)

    @jax.jit
    def grads(score):
        p = jax.nn.sigmoid(score)
        return p - y, p * (1 - p)

    def one(score):
        g, h = grads(score)
        tree, leaf_id, delta = og.grow_tree_ordered(
            bins, num_bin, is_cat, feat_mask, g, h, w,
            jnp.float32(0.1), params, bins_rm=bins_rm)
        return score + delta

    t0 = time.time()
    score = one(score)
    jax.block_until_ready(score)
    print(f"variant={sorted(VARIANT)} compile+first={time.time() - t0:.1f}s")

    t0 = time.time()
    for _ in range(ITERS):
        score = one(score)
    jax.block_until_ready(score)
    dt = (time.time() - t0) / ITERS
    print(f"variant={sorted(VARIANT)} per_tree_ms={dt * 1e3:.1f}")


if __name__ == "__main__":
    main()
