"""In-loop microbench of partition primitives on the live TPU.

Times each primitive inside a data-dependent fori_loop (output feeds the
next iteration's input) so axon's dispatch caching cannot short-circuit
(docs/BENCH_NOTES_r02.md methodology warning).  Reports ns/row.
"""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

P = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
REPS = 30


def timeit(name, fn, *args):
    jfn = jax.jit(fn)
    out = jax.block_until_ready(jfn(*args))      # compile + warm
    # chain: the timed call's input is the warm call's OUTPUT, so the
    # dispatch differs from the warm one and axon cannot replay it
    args2 = (out,) + args[1:]
    t0 = time.time()
    out = jax.block_until_ready(jfn(*args2))
    dt = (time.time() - t0) / REPS
    print(f"{name:28s} {dt * 1e3:8.3f} ms  {dt / P * 1e9:7.2f} ns/row")
    return out


rng = np.random.RandomState(0)
idx0 = jnp.asarray(rng.permutation(P).astype(np.int32))
key0 = jnp.asarray(rng.randint(0, 2, P).astype(np.uint8))
words = [jnp.asarray(rng.randint(-2**31, 2**31 - 1, P, np.int64)
                     .astype(np.int32)) for _ in range(11)]
mat_u8 = jnp.asarray(rng.randint(0, 255, (P, 28)).astype(np.uint8))
mat_w = jnp.stack(words, axis=1)  # [P, 11] i32


def loop(body):
    def fn(x, *rest):
        def step(_, c):
            return body(c, *rest)
        return jax.lax.fori_loop(0, REPS, step, x)
    return fn


# 2-op stable sort (u8 key + i32 payload)
timeit("sort2 (u8,i32)", loop(
    lambda i, k: jax.lax.sort((k, i), num_keys=1, is_stable=True)[1]),
    idx0, key0)

# 12-op stable sort (the round-2 partition)
def sort12(ws_key):
    k = ws_key[:P].astype(jnp.uint8)
    ops = (k,) + tuple(words)
    out = jax.lax.sort(ops, num_keys=1, is_stable=True)
    return out[1] + out[2]
timeit("sort12 (u8,11xi32)", loop(lambda i: sort12(i)), idx0)

# 1-D i32 gather
timeit("take1d i32", loop(lambda i: jnp.take(words[0], i) ^ i), idx0)

# 1-D i32 gather via [P,1] 2-D form
timeit("take2d [P,1] i32", loop(
    lambda i: jnp.take(words[0][:, None], i, axis=0)[:, 0] ^ i), idx0)

# 2-D row gather [P, 28] u8
timeit("take2d [P,28] u8", loop(
    lambda i: (jnp.take(mat_u8, i, axis=0)[:, 0].astype(jnp.int32) ^ i)),
    idx0)

# 2-D row gather [P, 11] i32
timeit("take2d [P,11] i32", loop(
    lambda i: jnp.take(mat_w, i, axis=0)[:, 0] ^ i), idx0)

# 11 x 1-D i32 gathers (permutation apply, word-major)
def apply_perm(i):
    acc = i
    for w in words:
        acc = acc ^ jnp.take(w, i)
    return acc
timeit("11x take1d i32", loop(apply_perm), idx0)

# scatter 1-D i32 (unique indices)
timeit("scatter1d i32", loop(
    lambda i: jnp.zeros(P, jnp.int32).at[i].set(i, unique_indices=True)),
    idx0)

# cumsum i32 (prefix pass reference)
timeit("cumsum i32", loop(lambda i: jnp.cumsum(i) ^ i), idx0)

# contiguous copy reference
timeit("copy i32", loop(lambda i: i + 1), idx0)
